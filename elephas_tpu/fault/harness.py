"""Chaos-injection harness (ISSUE 3 tentpole, part 3).

Executable fault machinery around a :class:`~elephas_tpu.fault.plan.
FaultPlan`: a :class:`RestartablePS` that can crash-and-recover a live
parameter server on its original port (journal replay), a
:class:`PSKiller` that triggers the crash mid-training and measures
recovery from real server counters, and :func:`run_chaos_training`,
which drives a real ``AsynchronousSparkWorker`` against all of it —
shared by ``tests/test_fault_tolerance.py`` and ``bench.py --preset
faults`` so the tested faults and the benchmarked faults are the same
code path.

Everything here is deterministic given ``(plan.seed, data seed)`` up to
scheduler timing: the data, the model init, the duplicate schedule, and
the kill trigger (an applied-update count, not a wall-clock timer) are
all seeded; only the exact interleaving of the kill with the worker's
in-flight op varies, which is precisely the nondeterminism the
recovery machinery must absorb.
"""

from __future__ import annotations

import itertools
import logging
import os
import tempfile
import threading
import time

import numpy as np

from elephas_tpu import telemetry
from elephas_tpu.fault.plan import FaultPlan
from elephas_tpu.utils import sockets

logger = logging.getLogger(__name__)

# per-run trace ids for the chaos harness (ISSUE 13): the harness is
# the "edge" of a chaos training run the way the gateway is for a
# request — one deterministic id per run (process-monotonic counter,
# no pids/wall time), propagated over the PS wire so worker pushes,
# server applies, and journal writes merge into one causal story
_chaos_run_ids = itertools.count()


def _chaos_trace_id(kind: str, transport: str, seed: int) -> str:
    return f"chaos-{kind}-{transport}-s{seed}-r{next(_chaos_run_ids)}"


def _require_telemetry(what: str) -> None:
    """The chaos machinery reads registry-backed counters for its kill
    trigger and recovery stamps (``updates_applied`` polling) — under
    telemetry null mode those read 0 and the killer would never fire.
    Refuse loudly instead of hanging."""
    if telemetry.null_mode():
        raise RuntimeError(
            f"{what} requires telemetry: the kill trigger and recovery "
            f"detection poll registry-backed counters, which read 0 "
            f"under null mode — call telemetry.set_null(False) first"
        )


def recovery_windows_from_trace(
    tracer=None, since_seq: int = 0, shard: int | None = None
) -> list:
    """Kill→first-post-restart-apply windows (seconds) read from the
    trace stream — the ``chaos.recovery`` spans :class:`PSKiller` /
    :class:`ShardKiller` record, filtered to those that actually
    observed recovery. With ``shard`` set, only that shard's spans
    (the ``shard`` arg the sharded killer stamps) are returned — how
    ``bench.py --preset faults --faults-shards N`` reports per-shard
    windows (ISSUE 5/6: the bench reads the same stream an operator's
    trace viewer shows, not bespoke harness counters)."""
    tracer = tracer or telemetry.tracer()
    return [
        float(e["dur"])
        for e in tracer.events(since_seq=since_seq, name="chaos.recovery")
        if e["args"].get("recovered")
        and (shard is None or e["args"].get("shard") == int(shard))
    ]


class RestartablePS:
    """Owns a (journaled) parameter server that can be killed like a
    crash — no terminal journal flush — and restarted on the SAME port,
    replaying the journal.

    Counters (`updates_applied`, `updates_duplicate`) accumulate across
    incarnations so callers read totals, not just the survivor's.
    """

    def __init__(
        self,
        server_cls,
        weights,
        mode: str = "asynchronous",
        journal_dir: str | None = None,
        journal_every: int = 2,
        lease_timeout: float = 30.0,
    ):
        _require_telemetry("RestartablePS")
        self._server_cls = server_cls
        self._weights = [np.asarray(w) for w in weights]
        self._mode = mode
        self._journal_dir = journal_dir
        self._journal_every = journal_every
        self._lease_timeout = lease_timeout
        self._dead_counts = {"updates_applied": 0, "updates_duplicate": 0}
        self.kills = 0
        self.restarts = 0
        self.t_killed: float | None = None
        self.t_recovered: float | None = None
        self.server = self._spawn(port=0)
        self.server.start()
        self.port = self.server.port

    def _spawn(self, port: int):
        return self._server_cls(
            self._weights,
            mode=self._mode,
            port=port,
            journal_dir=self._journal_dir,
            journal_every=self._journal_every,
            lease_timeout=self._lease_timeout,
        )

    def _absorb_counts(self, server) -> None:
        self._dead_counts["updates_applied"] += server.updates_applied
        self._dead_counts["updates_duplicate"] += server.updates_duplicate

    def kill(self) -> None:
        """Crash the server: stop serving WITHOUT a terminal journal
        flush, so recovery replays the last periodic snapshot (the
        honest crash case — a clean ``stop()`` would hide journal lag)."""
        server, self.server = self.server, None
        if server is None:
            return
        self.t_killed = time.monotonic()
        self.kills += 1
        telemetry.emit("chaos.ps_kill", port=self.port, kills=self.kills)
        server.stop(flush_journal=False)
        # absorb AFTER stop: an op in flight at the kill may still
        # complete its apply while connections sever
        self._absorb_counts(server)
        logger.info("chaos: parameter server killed on port %d", self.port)

    def restart(self) -> None:
        server = self._spawn(port=self.port)
        server.start()
        self.server = server
        self.restarts += 1
        telemetry.emit(
            "chaos.ps_restart", port=self.port,
            journal_restored=server.restored_from_journal,
        )
        logger.info(
            "chaos: parameter server restarted on port %d (journal "
            "restored: %s)", self.port, server.restored_from_journal,
        )

    def counters(self) -> dict[str, int]:
        out = dict(self._dead_counts)
        if self.server is not None:
            out["updates_applied"] += self.server.updates_applied
            out["updates_duplicate"] += self.server.updates_duplicate
        return out

    @property
    def recovery_s(self) -> float | None:
        """Kill → first post-restart applied update, from real
        timestamps (None until both happened)."""
        if self.t_killed is None or self.t_recovered is None:
            return None
        return self.t_recovered - self.t_killed

    def get_parameters(self):
        return self.server.get_parameters()

    def stop(self) -> None:
        if self.server is not None:
            self._absorb_counts(self.server)
            self.server.stop()
            self.server = None


class PSKiller(threading.Thread):
    """Kills the PS once it has applied ``after_updates`` more updates
    (beyond ``baseline``), restarts it after ``restart_delay_s``, and
    stamps ``ps.t_recovered`` at the first update the reborn server
    applies."""

    def __init__(
        self,
        ps: RestartablePS,
        after_updates: int,
        restart_delay_s: float = 0.5,
        baseline: int = 0,
        poll_s: float = 0.01,
    ):
        super().__init__(name="elephas-chaos-pskiller", daemon=True)
        self.ps = ps
        self.after_updates = int(after_updates)
        self.restart_delay_s = float(restart_delay_s)
        self.baseline = int(baseline)
        self.poll_s = float(poll_s)
        self._cancel = threading.Event()

    def cancel(self) -> None:
        self._cancel.set()

    def _wait_for_updates(self, threshold: int) -> bool:
        while not self._cancel.is_set():
            server = self.ps.server
            if server is not None and server.updates_applied >= threshold:
                return True
            time.sleep(self.poll_s)
        return False

    def run(self) -> None:
        if not self._wait_for_updates(self.baseline + self.after_updates):
            return
        # the kill→first-post-restart-apply window is ONE span on the
        # shared trace timeline (ISSUE 5): the bench and tests read the
        # recovery number from the same stream an operator's trace
        # viewer shows. `recovered` is stamped on the span so a
        # cancelled run never masquerades as a measured recovery.
        with telemetry.trace_span(
            "chaos.recovery", port=self.ps.port,
            after_updates=self.after_updates,
            restart_delay_s=self.restart_delay_s,
        ) as span:
            self.ps.kill()
            time.sleep(self.restart_delay_s)
            self.ps.restart()
            recovered = self._wait_for_updates(1)
            span.set(recovered=recovered)
        if recovered:
            self.ps.t_recovered = time.monotonic()


class EngineStaller:
    """Deliberate serving-engine stall injection (ISSUE 13): while
    active, ``engine.step()`` is replaced by a do-nothing stand-in —
    queued work stays queued, tokens stop landing — which is exactly
    the signature the watchdog's ``decode_stall``/``queue_stall``
    rules must detect (and must CLEAR once the context exits and real
    steps resume). A fault injector like :class:`PSKiller`: the
    harness drives control flow by design; telemetry only observes.

    Use as a context manager::

        with EngineStaller(engine):
            ...probe /healthz, assert the anomaly fired...
        ...drain, assert it cleared...
    """

    def __init__(self, engine, sleep_s: float = 0.01):
        _require_telemetry("EngineStaller")
        self.engine = engine
        self.sleep_s = float(sleep_s)

    def __enter__(self) -> "EngineStaller":
        telemetry.emit(
            "chaos.engine_stall", engine=self.engine.telemetry_label,
        )

        def stalled_step():
            # keep the driver loop cheap while stalled (it spins on
            # has_work); queued requests stay queued, nothing decodes
            time.sleep(self.sleep_s)
            return []

        # instance attribute shadows the bound method; __exit__
        # deletes it to restore the real step
        self.engine.step = stalled_step
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        del self.engine.step
        telemetry.emit(
            "chaos.engine_resume", engine=self.engine.telemetry_label,
        )


class WatchdogPoller:
    """Evaluate a watchdog at a fixed cadence on a daemon thread for
    the duration of a chaos run — the end-to-end wiring the ISSUE-13
    acceptance asks for (shard kill ⇒ anomaly with the right label ⇒
    clear on recovery), shared by ``run_sharded_chaos_training`` and
    the tests so the tested detection is the benchmarked detection."""

    def __init__(self, watchdog, interval_s: float = 0.05):
        self.watchdog = watchdog
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="elephas-watchdog-poll", daemon=True
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            self.watchdog.evaluate()
            self._stop.wait(self.interval_s)

    def __enter__(self) -> "WatchdogPoller":
        self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)


class ReplicaKiller(threading.Thread):
    """Kill one fleet-router serving replica mid-stream (ISSUE 14
    chaos): a daemon thread watches the router's PLAIN delivered-token
    counter (host truth, not a registry series — the trigger never
    reads telemetry) and, once the fleet has streamed
    ``after_tokens`` tokens, abandons the named replica through
    ``router.kill_replica()`` — driver dead, engine state lost,
    exactly a crashed process — which re-drives the survivors.
    Telemetry is still REQUIRED: the kill's evidence trail (the
    ``chaos.replica_kill`` instant, the router's replica-up gauge the
    ``replica_down`` watchdog rule fires on) is the point of running
    chaos at all.

    ``killed`` is set after the kill; ``redriven`` records how many
    in-flight requests moved. Like :class:`PSKiller`, the trigger is a
    COUNT, not a wall-clock timer: the same workload kills at the same
    logical point on any box speed."""

    def __init__(self, router, replica: str, after_tokens: int = 8,
                 poll_s: float = 0.005):
        super().__init__(name="elephas-replica-killer", daemon=True)
        _require_telemetry("ReplicaKiller")
        self.router = router
        self.replica = str(replica)
        self.after_tokens = int(after_tokens)
        self.poll_s = float(poll_s)
        self.killed = threading.Event()
        self.redriven: int | None = None
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            if self.router.tokens_delivered >= self.after_tokens:
                telemetry.emit(
                    "chaos.replica_kill", replica=self.replica,
                    after_tokens=self.after_tokens,
                )
                self.redriven = self.router.kill_replica(self.replica)
                self.killed.set()
                return
            self._halt.wait(self.poll_s)

    def cancel(self) -> None:
        self._halt.set()
        self.join(timeout=15)


# -- sharded chaos (ISSUE 6) ---------------------------------------------


class ShardedRestartablePS:
    """N per-shard restartable servers — the sharded sibling of
    :class:`RestartablePS`: each shard can be crash-killed (no terminal
    journal flush) and restarted on its original port, replaying ONLY
    its own journal (``journal_dir/shard-<i>/``).

    **Hot-standby mode** (``standby_delay_s``): a daemon watcher
    restarts any killed shard automatically after the delay — the
    kill/restart decision decouples from whoever killed it (the
    production shape: a supervisor reschedules the dead shard while
    clients park that slice's sequenced pushes and resend on return).

    Counters accumulate across incarnations per shard, so callers read
    totals — and can read the OTHER shards' totals mid-outage, which is
    the partial-progress evidence the acceptance criteria ask for.
    """

    def __init__(
        self,
        server_cls,
        weights,
        num_shards: int,
        mode: str = "asynchronous",
        journal_dir: str | None = None,
        journal_every: int = 2,
        lease_timeout: float = 30.0,
        standby_delay_s: float | None = None,
        host: str = "127.0.0.1",
    ):
        from elephas_tpu.parameter.sharding import (
            ShardMap,
            shard_journal_dir,
        )

        _require_telemetry("ShardedRestartablePS")
        self._server_cls = server_cls
        self.shard_map = ShardMap.from_weights(weights, num_shards)
        self._slices = self.shard_map.scatter(
            [np.asarray(w) for w in weights]
        )
        self._mode = mode
        self._journal_dirs = [
            shard_journal_dir(journal_dir, i) if journal_dir else None
            for i in range(num_shards)
        ]
        self._journal_every = journal_every
        self._lease_timeout = lease_timeout
        self.host = host
        self.num_shards = num_shards
        self.kills = [0] * num_shards
        self.restarts = [0] * num_shards
        # per-shard kill/recovery timestamps — the counters-side
        # cross-check for the trace-span recovery windows (PR 5 shape)
        self.t_killed: list[float | None] = [None] * num_shards
        self.t_recovered: list[float | None] = [None] * num_shards
        self._dead_counts = [
            {"updates_applied": 0, "updates_duplicate": 0}
            for _ in range(num_shards)
        ]
        self._lock = threading.Lock()
        self.servers: list = [None] * num_shards
        for i in range(num_shards):
            self.servers[i] = self._spawn(i, port=0)
            self.servers[i].start()
        self.ports = [s.port for s in self.servers]
        self._standby_delay = standby_delay_s
        self._standby_stop = threading.Event()
        self._standby = None
        if standby_delay_s is not None:
            self._standby = threading.Thread(
                target=self._standby_loop,
                name="elephas-chaos-shard-standby", daemon=True,
            )
            self._standby.start()

    def _spawn(self, shard: int, port: int):
        return self._server_cls(
            self._slices[shard],
            mode=self._mode,
            port=port,
            journal_dir=self._journal_dirs[shard],
            journal_every=self._journal_every,
            lease_timeout=self._lease_timeout,
            shard_id=shard,
            num_shards=self.num_shards,
            shard_signature=self.shard_map.signature(),
        )

    @property
    def endpoints(self) -> str:
        return ",".join(f"{self.host}:{p}" for p in self.ports)

    def kill(self, shard: int) -> None:
        """Crash shard ``shard``: sever its connections, skip the
        terminal journal flush (recovery must replay the last periodic
        snapshot — the honest crash case)."""
        with self._lock:
            server, self.servers[shard] = self.servers[shard], None
        if server is None:
            return
        self.t_killed[shard] = time.monotonic()
        self.kills[shard] += 1
        telemetry.emit(
            "chaos.ps_kill", port=self.ports[shard], shard=shard,
            kills=self.kills[shard],
        )
        server.stop(flush_journal=False)
        # absorb AFTER stop: an op in flight at the kill may still
        # complete its apply while connections sever
        self._absorb(shard, server)
        logger.info(
            "chaos: shard %d killed on port %d", shard, self.ports[shard]
        )

    def restart(self, shard: int) -> None:
        server = self._spawn(shard, port=self.ports[shard])
        server.start()
        with self._lock:
            self.servers[shard] = server
        self.restarts[shard] += 1
        telemetry.emit(
            "chaos.ps_restart", port=self.ports[shard], shard=shard,
            journal_restored=server.restored_from_journal,
        )
        logger.info(
            "chaos: shard %d restarted on port %d (journal restored: "
            "%s)", shard, self.ports[shard],
            server.restored_from_journal,
        )

    def _standby_loop(self) -> None:
        """Hot standby: bring any killed shard back after the delay."""
        while not self._standby_stop.is_set():
            for i in range(self.num_shards):
                if self.servers[i] is None and self.kills[i] > self.restarts[i]:
                    if self._standby_stop.wait(self._standby_delay):
                        return
                    if self.servers[i] is None:
                        self.restart(i)
            self._standby_stop.wait(0.01)

    def _absorb(self, shard: int, server) -> None:
        self._dead_counts[shard]["updates_applied"] += server.updates_applied
        self._dead_counts[shard]["updates_duplicate"] += (
            server.updates_duplicate
        )

    def shard_counters(self, shard: int) -> dict[str, int]:
        out = dict(self._dead_counts[shard])
        server = self.servers[shard]
        if server is not None:
            out["updates_applied"] += server.updates_applied
            out["updates_duplicate"] += server.updates_duplicate
        return out

    def counters(self) -> dict[str, int]:
        totals = {"updates_applied": 0, "updates_duplicate": 0}
        for i in range(self.num_shards):
            for k, v in self.shard_counters(i).items():
                totals[k] += v
        return totals

    def get_parameters(self, timeout_s: float = 30.0):
        """Gather the full weight list. A shard awaiting its hot-standby
        restart is waited for (bounded) rather than crashing a caller
        who raced the watcher; a dead shard nobody will restart is a
        loud error, not an AttributeError on ``None``."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                servers = list(self.servers)
            down = [i for i, s in enumerate(servers) if s is None]
            if not down:
                return self.shard_map.gather(
                    [s.get_parameters() for s in servers]
                )
            if self._standby is None or time.monotonic() >= deadline:
                raise RuntimeError(
                    f"shard(s) {down} are killed and not restarted — "
                    f"cannot gather the full weight list (restart them, "
                    f"or run hot standby and retry)"
                )
            time.sleep(0.01)

    def stop(self) -> None:
        self._standby_stop.set()
        if self._standby is not None:
            self._standby.join(timeout=10)
        for i, server in enumerate(self.servers):
            if server is not None:
                self._absorb(i, server)
                server.stop()
                self.servers[i] = None


class DeployChaosStore:
    """Ledger-facing store view over a :class:`ShardedRestartablePS`
    (ISSUE 20): lets a
    :class:`~elephas_tpu.deploy.versions.VersionLedger` publish weight
    generations THROUGH the chaos harness, so a shard can be
    crash-killed mid-deployment.

    Semantics under a kill: a dead shard simply MISSES the publication
    (weights are state, not a sequenced delta — there is nothing to
    park and replay). After its journal restore it reports the
    generation it last journaled, the store shows a MIXED version cut
    (which every :class:`~elephas_tpu.deploy.subscriber.WeightSubscriber`
    refuses to apply — serving never tears), and the NEXT publication
    re-converges every shard. The subscriber's version compare makes
    that convergence idempotent: one apply per generation, never two.
    """

    def __init__(self, harness: ShardedRestartablePS):
        self.harness = harness

    @property
    def servers(self) -> list:
        """Live shard servers — the unit the ledger journals at. Dead
        shards are absent (their journal was written at the last
        publication they saw; re-snapshotting a corpse is meaningless)."""
        return [s for s in self.harness.servers if s is not None]

    def set_weights(self, weights, weight_version: int | None = None):
        """Scatter one generation onto every LIVE shard. Dead shards
        are skipped loudly — they rejoin at an older generation and the
        mixed cut is visible on ``status()`` until re-published."""
        slices = self.harness.shard_map.scatter(
            [np.asarray(w) for w in weights]
        )
        dead = [
            i for i, s in enumerate(self.harness.servers) if s is None
        ]
        if dead:
            logger.warning(
                "deploy chaos: publishing generation %s past dead "
                "shard(s) %s — they rejoin on an older generation "
                "until the next publication", weight_version, dead,
            )
        for server, piece in zip(self.harness.servers, slices):
            if server is not None:
                server.set_weights(piece, weight_version=weight_version)

    def get_parameters(self):
        return self.harness.get_parameters()

    def status(self) -> list[dict]:
        """Per-LIVE-shard status, shard order (dead shards absent —
        the wire-facing unreachability story belongs to the clients)."""
        return [
            s.status() for s in self.harness.servers if s is not None
        ]


class ShardKiller(threading.Thread):
    """Kills ONE shard once it has applied ``after_updates`` more
    updates (beyond ``baseline``), then waits for its recovery —
    restarting it itself after ``restart_delay_s`` unless the
    :class:`ShardedRestartablePS` runs hot standby (then the standby
    owns the restart and this thread only observes). The
    kill→first-post-restart-apply window lands as ONE
    ``chaos.recovery`` span stamped with ``shard=``, and the OTHER
    shards' applied counts are snapshotted at kill and at recovery —
    ``other_progress`` is the partial-progress proof."""

    def __init__(
        self,
        ps: ShardedRestartablePS,
        shard: int,
        after_updates: int,
        restart_delay_s: float = 0.5,
        baseline: int = 0,
        poll_s: float = 0.01,
    ):
        super().__init__(name="elephas-chaos-shardkiller", daemon=True)
        self.ps = ps
        self.shard = int(shard)
        self.after_updates = int(after_updates)
        self.restart_delay_s = float(restart_delay_s)
        self.baseline = int(baseline)
        self.poll_s = float(poll_s)
        self.other_progress: dict[int, int] | None = None
        self.recovered = False
        self._cancel = threading.Event()

    def cancel(self) -> None:
        self._cancel.set()

    def _applied(self) -> int:
        return self.ps.shard_counters(self.shard)["updates_applied"]

    def _wait_applied(self, threshold: int) -> bool:
        while not self._cancel.is_set():
            if self._applied() >= threshold:
                return True
            time.sleep(self.poll_s)
        return False

    def _wait_reborn_applied(self) -> bool:
        # Recovery = the REBORN incarnation's OWN first apply (its
        # counter starts at zero; the journal meta is informational).
        # Waiting on the absorbed per-shard total instead would race:
        # an apply in flight at the kill still lands while connections
        # sever and is absorbed into the dead counts, satisfying an
        # at-kill+1 threshold with no post-restart apply at all — and
        # the trace-vs-counters cross-check could not catch it, both
        # sides deriving from the same too-early event.
        while not self._cancel.is_set():
            server = self.ps.servers[self.shard]
            if server is not None and server.updates_applied >= 1:
                return True
            time.sleep(self.poll_s)
        return False

    def _others(self) -> dict[int, int]:
        return {
            i: self.ps.shard_counters(i)["updates_applied"]
            for i in range(self.ps.num_shards)
            if i != self.shard
        }

    def run(self) -> None:
        if not self._wait_applied(self.baseline + self.after_updates):
            return
        standby = self.ps._standby is not None
        with telemetry.trace_span(
            "chaos.recovery", shard=self.shard,
            port=self.ps.ports[self.shard],
            after_updates=self.after_updates,
            restart_delay_s=self.restart_delay_s,
            standby=standby,
        ) as span:
            others_at_kill = self._others()
            self.ps.kill(self.shard)
            if not standby:
                time.sleep(self.restart_delay_s)
                self.ps.restart(self.shard)
            # recovery = the REBORN shard applies (resent/parked
            # updates land); under standby the restart itself is the
            # watcher's, we only observe
            self.recovered = self._wait_reborn_applied()
            span.set(recovered=self.recovered)
        if self.recovered:
            self.ps.t_recovered[self.shard] = time.monotonic()
            self.other_progress = {
                i: n - others_at_kill[i]
                for i, n in self._others().items()
            }


def run_sharded_chaos_training(
    transport: str = "socket",
    num_shards: int = 2,
    rows: int = 256,
    epochs: int = 2,
    batch_size: int = 64,
    seed: int = 0,
    plan: FaultPlan | None = None,
    journal_dir: str | None = None,
    journal_every: int = 1,
    mode: str = "asynchronous",
    ps_retries: int = 8,
    standby: bool = False,
    trace_export: str | None = None,
    watch: bool = False,
) -> dict:
    """One real async-worker run against a SHARDED restartable PS —
    the multi-shard sibling of :func:`run_chaos_training`, shared by
    ``tests/test_ps_sharding.py`` and ``bench.py --preset faults
    --faults-shards N``.

    Under a plan with ``kill_ps_after_updates``, shard
    ``plan.kill_shard`` is crash-killed mid-run and recovers from its
    own journal (hot standby when ``standby=True``); the worker's
    sharded client parks that slice's pushes and keeps the other
    shards served. Returns per-shard counters, the per-shard recovery
    window read from the shard-stamped ``chaos.recovery`` trace span,
    and ``other_shards_progress_during_outage`` — updates the
    surviving shards applied inside the recovery window (the
    acceptance criterion's partial-progress proof).

    ISSUE 13: the whole run executes under one minted trace id
    (``trace_id`` in the result) which the sharded client forwards
    over the wire — worker sync spans, per-shard applies, and journal
    writes share it on the exported timeline. ``watch=True``
    additionally runs a default-rule
    :class:`~elephas_tpu.telemetry.watch.Watchdog` at 50ms cadence for
    the run's duration: the shard kill must surface as a
    ``ps_unreachable`` anomaly labeled with the killed shard, then
    clear once the parked pushes replay — the fired/cleared event
    streams and the final report ride back in the result
    (``watch_anomalies`` / ``watch_cleared`` / ``watch_report``).
    """
    from elephas_tpu.parameter.server import HttpServer, SocketServer
    from elephas_tpu.worker import AsynchronousSparkWorker

    _require_telemetry("run_sharded_chaos_training")
    trace_seq0 = telemetry.tracer().seq
    x, y, d, k = _chaos_data(seed, rows)
    model = _chaos_model(seed, d, k)
    server_cls = {"socket": SocketServer, "http": HttpServer}[transport]
    plan = plan or FaultPlan(seed=seed)
    ps = ShardedRestartablePS(
        server_cls,
        model.get_weights(),
        num_shards,
        mode=mode,
        journal_dir=journal_dir,
        journal_every=journal_every,
        standby_delay_s=plan.restart_delay_s if standby else None,
    )
    worker = AsynchronousSparkWorker(
        model.to_json(),
        train_config={"epochs": epochs, "batch_size": batch_size},
        frequency="batch",
        parameter_server_mode=transport,
        master=ps.endpoints,
        master_optimizer="adam",
        master_loss="sparse_categorical_crossentropy",
        ps_retries=ps_retries,
    )
    clients: list = []
    real_client = worker._client

    def chaotic_client(model=None):
        client = real_client(model)
        if plan.duplicate_fraction > 0.0:
            client.chaos_duplicate = plan.duplicate
        clients.append(client)
        return client

    worker._client = chaotic_client

    trace_id = _chaos_trace_id("sharded", transport, seed)
    watchdog = poller = None
    if watch:
        from elephas_tpu.telemetry.watch import Watchdog

        watchdog = Watchdog()
        poller = WatchdogPoller(watchdog)

    killer = None
    try:
        if poller is not None:
            poller.__enter__()
        with telemetry.trace_scope(trace_id):
            # warmup outside the timed window and before any chaos
            list(worker.train(iter(zip(x[:batch_size], y[:batch_size]))))
            baseline = ps.shard_counters(plan.kill_shard)[
                "updates_applied"
            ]
            if plan.kill_ps_after_updates is not None:
                killer = ShardKiller(
                    ps,
                    plan.kill_shard,
                    plan.kill_ps_after_updates,
                    restart_delay_s=plan.restart_delay_s,
                    baseline=baseline,
                )
                killer.start()
            t0 = time.perf_counter()
            list(worker.train(iter(zip(x, y))))
            dt = time.perf_counter() - t0
    finally:
        if killer is not None:
            if ps.kills[plan.kill_shard]:
                # the kill fired: the killer exits on its own at the
                # reborn shard's first apply — give it time to OBSERVE
                # before cancelling. On a fast box the whole post-kill
                # training can fit inside restart_delay_s, leaving the
                # final flush's replay as the recovery signal; an
                # eager cancel here raced that last ~10ms poll and
                # discarded a recovery that actually happened.
                killer.join(timeout=15)
            killer.cancel()
            killer.join(timeout=30)
        if poller is not None:
            poller.stop()
    if watchdog is not None:
        # a few post-run evaluations: PsUnreachableRule clears after
        # `clear_after` quiet looks, and the run may have ended inside
        # its hysteresis window
        for _ in range(4):
            watchdog.evaluate()
    try:
        per_shard = [ps.shard_counters(i) for i in range(num_shards)]
        final_weights = ps.get_parameters()
    finally:
        ps.stop()

    shard_windows = {
        i: recovery_windows_from_trace(since_seq=trace_seq0, shard=i)
        for i in range(num_shards)
    }
    if trace_export:
        n_events = telemetry.tracer().export_chrome_trace(
            trace_export, since_seq=trace_seq0
        )
        logger.info(
            "sharded chaos trace: %d events exported to %s",
            n_events, trace_export,
        )
    tracer = telemetry.tracer()
    watch_out = {}
    if watchdog is not None:
        watch_out = {
            "watch_anomalies": [
                dict(e["args"])
                for e in tracer.events(
                    since_seq=trace_seq0, name="watch.anomaly"
                )
            ],
            "watch_cleared": [
                dict(e["args"])
                for e in tracer.events(
                    since_seq=trace_seq0, name="watch.clear"
                )
            ],
            "watch_report": watchdog.report(),
        }
    killed = plan.kill_shard
    return {
        "transport": transport,
        "num_shards": num_shards,
        "rows": rows,
        "epochs": epochs,
        "seed": seed,
        "trace_id": trace_id,
        **watch_out,
        "dt_s": dt,
        "samples_per_s": rows * epochs / dt,
        "killed_shard": killed if plan.kill_ps_after_updates else None,
        "kills": list(ps.kills),
        "restarts": list(ps.restarts),
        "standby": standby,
        "recovery_s_by_shard": {
            i: (w[-1] if w else None) for i, w in shard_windows.items()
        },
        # counters-side cross-check (kill/recovery timestamp pair per
        # shard) for the trace-span windows above
        "recovery_s_counters_by_shard": {
            i: (
                None
                if ps.t_killed[i] is None or ps.t_recovered[i] is None
                else ps.t_recovered[i] - ps.t_killed[i]
            )
            for i in range(num_shards)
        },
        "updates_applied_by_shard": [
            c["updates_applied"] for c in per_shard
        ],
        "duplicates_skipped_by_shard": [
            c["updates_duplicate"] for c in per_shard
        ],
        "other_shards_progress_during_outage": (
            killer.other_progress if killer is not None else None
        ),
        "updates_resent": sum(c.updates_resent for c in clients),
        "duplicates_sent": sum(c.chaos_dups_sent for c in clients),
        "pending_final": [
            n for c in clients
            for n in getattr(c, "pending_counts", [])
        ],
        "updates_lost_final": sum(
            getattr(c, "updates_lost", 0) for c in clients
        ),
        "final_weights": final_weights,
        "data": (x, y),
    }


def run_elastic_membership(
    transport: str = "socket",
    num_shards: int = 2,
    rows: int = 192,
    batch_size: int = 32,
    seed: int = 0,
    join_after_periods: int = 2,
    journal_dir: str | None = None,
) -> dict:
    """Elastic data-parallel membership against a (sharded) PS: one
    worker runs the whole dataset, a second LEAVES mid-run (it trains
    only a head slice, flushes, closes — its lease then goes stale),
    and a third JOINS mid-run (starts after the early worker's
    departure, pulls the then-current weights, contributes the tail).
    No coordinator round-trip anywhere: registration is implicit in
    the first sequenced update and departure is just lease staleness —
    the PR 3 membership machinery carrying elastic DP (ISSUE 6).

    Returns the final per-shard membership view, applied/duplicate
    totals, and the final weights for convergence assertions.
    """
    from elephas_tpu.parameter.server import HttpServer, SocketServer
    from elephas_tpu.worker import AsynchronousSparkWorker

    _require_telemetry("run_elastic_membership")
    x, y, d, k = _chaos_data(seed, rows)
    model = _chaos_model(seed, d, k)
    server_cls = {"socket": SocketServer, "http": HttpServer}[transport]
    ps = ShardedRestartablePS(
        server_cls, model.get_weights(), num_shards,
        journal_dir=journal_dir,
    )

    def make_worker(client_id: str):
        return AsynchronousSparkWorker(
            model.to_json(),
            train_config={"epochs": 1, "batch_size": batch_size},
            frequency="batch",
            parameter_server_mode=transport,
            master=ps.endpoints,
            master_optimizer="adam",
            master_loss="sparse_categorical_crossentropy",
            client_id=client_id,
        )

    third = rows // 3
    joined = threading.Event()
    errors: list = []

    def steady():
        try:
            list(make_worker("steady").train(iter(zip(x, y))))
        except BaseException as e:  # surfaced below, never swallowed
            errors.append(("steady", e))

    def leaver():
        try:
            # trains only the head slice then closes: a mid-run
            # departure — flush() inside train() confirms delivery
            # first, so nothing it pushed is lost
            list(make_worker("leaver").train(
                iter(zip(x[:third], y[:third]))
            ))
        except BaseException as e:
            errors.append(("leaver", e))
        finally:
            joined.set()  # the joiner enters once the leaver is gone

    def joiner():
        joined.wait(timeout=60)
        try:
            list(make_worker("joiner").train(
                iter(zip(x[third:], y[third:]))
            ))
        except BaseException as e:
            errors.append(("joiner", e))

    threads = [
        threading.Thread(target=fn, daemon=True, name=f"elastic-{fn.__name__}")
        for fn in (steady, leaver, joiner)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        if errors:
            raise RuntimeError(f"elastic workers failed: {errors!r}")
        members = [s.members() for s in ps.servers]
        counters = ps.counters()
        final_weights = ps.get_parameters()
    finally:
        ps.stop()
    return {
        "members_by_shard": members,
        "updates_applied": counters["updates_applied"],
        "updates_duplicate": counters["updates_duplicate"],
        "final_weights": final_weights,
        "data": (x, y),
    }


# -- end-to-end chaos training -------------------------------------------


def _chaos_data(seed: int, rows: int, d: int = 16, k: int = 3):
    """Seeded separable blobs (the conftest recipe, self-contained so
    bench runs outside pytest)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * 2.0
    y = rng.integers(0, k, size=rows)
    x = (centers[y] + rng.normal(size=(rows, d)) * 0.6).astype(np.float32)
    return x, y.astype(np.int32), d, k


def _chaos_model(seed: int, d: int, k: int):
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras

    keras.utils.set_random_seed(seed)
    model = keras.Sequential(
        [
            keras.layers.Input((d,)),
            keras.layers.Dense(32, activation="relu"),
            keras.layers.Dense(k, activation="softmax"),
        ]
    )
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    return model


def run_chaos_training(
    transport: str = "socket",
    rows: int = 256,
    epochs: int = 2,
    batch_size: int = 64,
    seed: int = 0,
    plan: FaultPlan | None = None,
    journal_dir: str | None = None,
    journal_every: int = 2,
    mode: str = "asynchronous",
    ps_retries: int = 8,
    trace_export: str | None = None,
) -> dict:
    """One real async-worker training run under ``plan`` (or fault-free
    when ``plan`` is None) against a restartable, journaled PS.

    Returns real counters and timings: wall-clock + samples/sec of the
    timed (post-warmup) window, kill/restart/recovery timestamps,
    applied/duplicate counts aggregated across server incarnations, and
    the worker clients' lost/resent counters — plus the final server
    weights so callers can evaluate convergence. ``recovery_s_trace``
    is the kill→recovery window read from the trace stream (the
    ``chaos.recovery`` span), and ``trace_export`` dumps this run's
    events as Chrome-trace JSON — the kill, restart, recovery span,
    worker retries, and PS round-trips on one timeline.
    """
    from elephas_tpu.parameter.server import HttpServer, SocketServer
    from elephas_tpu.worker import AsynchronousSparkWorker

    _require_telemetry("run_chaos_training")
    trace_seq0 = telemetry.tracer().seq
    x, y, d, k = _chaos_data(seed, rows)
    model = _chaos_model(seed, d, k)
    server_cls = {"socket": SocketServer, "http": HttpServer}[transport]
    ps = RestartablePS(
        server_cls,
        model.get_weights(),
        mode=mode,
        journal_dir=journal_dir,
        journal_every=journal_every,
    )
    worker = AsynchronousSparkWorker(
        model.to_json(),
        train_config={"epochs": epochs, "batch_size": batch_size},
        frequency="batch",
        parameter_server_mode=transport,
        master=f"127.0.0.1:{ps.port}",
        master_optimizer="adam",
        master_loss="sparse_categorical_crossentropy",
        ps_retries=ps_retries,
    )
    clients: list = []
    real_client = worker._client

    def chaotic_client(model=None):
        client = real_client(model)
        if plan is not None and plan.duplicate_fraction > 0.0:
            client.chaos_duplicate = plan.duplicate
        clients.append(client)
        return client

    worker._client = chaotic_client

    killer = None
    previous_hook = None
    hook_installed = False
    # one trace id for the whole run (ISSUE 13): worker sync spans,
    # wire pushes, server applies, and journal writes merge into one
    # causal story on the exported timeline
    trace_id = _chaos_trace_id("single", transport, seed)
    try:
        with telemetry.trace_scope(trace_id):
            # warmup OUTSIDE the timed window and BEFORE any chaos:
            # keras compile + wire negotiation must not pollute
            # throughput or the kill trigger
            list(worker.train(iter(zip(x[:batch_size], y[:batch_size]))))
            baseline_updates = ps.counters()["updates_applied"]

            if plan is not None and plan.kill_ps_after_updates is not None:
                killer = PSKiller(
                    ps,
                    plan.kill_ps_after_updates,
                    restart_delay_s=plan.restart_delay_s,
                    baseline=baseline_updates,
                )
                killer.start()
            if plan is not None:
                hook = plan.make_socket_hook()
                if hook is not None:
                    previous_hook = sockets.set_fault_hook(hook)
                    hook_installed = True

            t0 = time.perf_counter()
            list(worker.train(iter(zip(x, y))))
            dt = time.perf_counter() - t0
    finally:
        if hook_installed:
            sockets.set_fault_hook(previous_hook)
        if killer is not None:
            if ps.kills:
                # fired: let the killer observe the reborn server's
                # first apply before cancelling (see the sharded
                # harness — eager cancel raced the final flush's
                # replay on fast boxes)
                killer.join(timeout=15)
            killer.cancel()
            killer.join(timeout=30)
    try:
        counters = ps.counters()
        final_weights = ps.get_parameters()
    finally:
        ps.stop()

    trace_windows = recovery_windows_from_trace(since_seq=trace_seq0)
    if trace_export:
        n_events = telemetry.tracer().export_chrome_trace(
            trace_export, since_seq=trace_seq0
        )
        logger.info(
            "chaos trace: %d events exported to %s", n_events, trace_export
        )

    return {
        "transport": transport,
        "rows": rows,
        "epochs": epochs,
        "seed": seed,
        "trace_id": trace_id,
        "dt_s": dt,
        "samples_per_s": rows * epochs / dt,
        # kill→recovery read from the trace stream (ISSUE 5): the
        # number the bench reports, sourced from the same events an
        # operator's trace viewer shows
        "recovery_s_trace": trace_windows[-1] if trace_windows else None,
        "updates_applied": counters["updates_applied"] - baseline_updates,
        "duplicates_skipped": counters["updates_duplicate"],
        "updates_resent": sum(c.updates_resent for c in clients),
        "duplicates_sent": sum(c.chaos_dups_sent for c in clients),
        "updates_lost_final": sum(
            getattr(c, "updates_lost", 0) for c in clients
        ),
        "kills": ps.kills,
        "restarts": ps.restarts,
        "recovery_s": ps.recovery_s,
        "journal_restored": (
            ps.restarts > 0 and journal_dir is not None
        ),
        "final_weights": final_weights,
        "data": (x, y),
    }


def measure_faults(
    transport: str = "socket",
    rows: int = 256,
    epochs: int = 2,
    batch_size: int = 64,
    seed: int = 0,
    kill_after_updates: int | None = None,
    restart_delay_s: float = 0.75,
    duplicate_fraction: float = 0.25,
    trace_export: str | None = None,
):
    """``bench.py --preset faults`` backend: one fault-free run and one
    chaos run (PS kill+restart mid-epoch, a seeded fraction of update
    frames duplicated on the wire, periodic wire delays) on the same
    seeded data/model. Returns ``(clean, faulted, plan)`` — the caller
    owns the JSON contract and the credibility gate."""
    from elephas_tpu.fault.plan import SocketFaults

    clean = run_chaos_training(
        transport, rows=rows, epochs=epochs, batch_size=batch_size,
        seed=seed, plan=None,
    )
    if kill_after_updates is None:
        # land the kill mid-epoch, around a third into the sync stream
        periods = max(1, -(-rows // batch_size)) * epochs
        kill_after_updates = max(2, periods // 3)
    plan = FaultPlan(
        seed=seed,
        kill_ps_after_updates=kill_after_updates,
        restart_delay_s=restart_delay_s,
        duplicate_fraction=duplicate_fraction,
        socket_faults=SocketFaults(delay_every=13, delay_ms=4.0),
    )
    with tempfile.TemporaryDirectory(prefix="elephas-faults-") as jdir:
        faulted = run_chaos_training(
            transport,
            rows=rows,
            epochs=epochs,
            batch_size=batch_size,
            seed=seed,
            plan=plan,
            journal_dir=jdir,
            trace_export=trace_export,
        )
    return clean, faulted, plan


def measure_sharded_faults(
    transport: str = "socket",
    num_shards: int = 2,
    rows: int = 256,
    epochs: int = 2,
    batch_size: int = 64,
    seed: int = 0,
    kill_after_updates: int | None = None,
    restart_delay_s: float = 0.75,
    duplicate_fraction: float = 0.25,
    kill_shard: int = 0,
    standby: bool = False,
    trace_export: str | None = None,
):
    """``bench.py --preset faults --faults-shards N`` backend (ISSUE
    6): one fault-free SHARDED run and one chaos run on the same
    seeded data/model, where only shard ``kill_shard`` is crash-killed
    mid-run (plus a seeded fraction of duplicated update frames on
    every shard) and recovers from its own journal. Returns
    ``(clean, faulted, plan)``; the caller owns the JSON contract and
    the credibility gates (per-shard trace-vs-counters agreement,
    surviving-shard progress, exactly-once totals)."""
    clean = run_sharded_chaos_training(
        transport, num_shards=num_shards, rows=rows, epochs=epochs,
        batch_size=batch_size, seed=seed, plan=None,
    )
    if kill_after_updates is None:
        # land the kill mid-epoch, around a third into the sync stream
        # (every sync period touches every shard, so per-shard applied
        # counts track the period count)
        periods = max(1, -(-rows // batch_size)) * epochs
        kill_after_updates = max(2, periods // 3)
    plan = FaultPlan(
        seed=seed,
        kill_ps_after_updates=kill_after_updates,
        restart_delay_s=restart_delay_s,
        duplicate_fraction=duplicate_fraction,
        kill_shard=kill_shard,
    )
    with tempfile.TemporaryDirectory(prefix="elephas-shard-faults-") as jdir:
        faulted = run_sharded_chaos_training(
            transport,
            num_shards=num_shards,
            rows=rows,
            epochs=epochs,
            batch_size=batch_size,
            seed=seed,
            plan=plan,
            journal_dir=jdir,
            standby=standby,
            trace_export=trace_export,
        )
    return clean, faulted, plan
