"""Chaos-injection harness + fault-model primitives (ISSUE 3).

``plan`` is the declarative side — deterministic, seedable
:class:`FaultPlan` objects describing PS crashes, wire faults,
duplicated update frames, and worker-partition loss. ``harness`` is
the executable side — :class:`RestartablePS`, :class:`PSKiller`, and
:func:`run_chaos_training` drive real servers/workers under a plan,
shared by the chaos test suite and ``bench.py --preset faults``.

The production fault-tolerance machinery itself lives where the
failures happen: journaled restartable servers in
:mod:`elephas_tpu.parameter.server`, sequence-ID idempotent clients in
:mod:`elephas_tpu.parameter.client`, the supervised worker retry in
:mod:`elephas_tpu.worker`, and the driver's failure budget in
:mod:`elephas_tpu.spark_model`. This package only *injects* faults.
"""

from elephas_tpu.fault.plan import (  # noqa: F401
    FaultBudgetExceeded,
    FaultPlan,
    SocketFaults,
    WorkerFault,
    active_plan,
    check_partition,
    use_plan,
)
from elephas_tpu.fault.harness import (  # noqa: F401
    DeployChaosStore,
    PSKiller,
    ReplicaKiller,
    RestartablePS,
    ShardKiller,
    ShardedRestartablePS,
    measure_faults,
    measure_sharded_faults,
    run_chaos_training,
    run_elastic_membership,
    run_sharded_chaos_training,
)
