"""Sequence-parallel long-prompt prefill for the serving engine
(ISSUE 11, tentpole part 2).

A single device's chunk prefill bounds how long a prompt the engine can
ingest in reasonable TTFT: the O(T²) attention term runs on one chip no
matter how the chunks are scheduled. This module removes that ceiling
the way training already does (``parallel/sequence.py``): the prompt's
sequence axis shards over an SP mesh axis, attention runs as a ring
(``ops/ring_attention.py`` — KV shards rotate over ICI, queries stay
put) or as Ulysses (``ops/ulysses.py`` — two all-to-alls around
full-sequence attention per head group), and every other op is
token-local so GSPMD runs it on the shards for free.

The engine uses exactly ONE program from here:
:func:`sp_prefill_forward` — a whole (power-of-two padded) prompt
forward that returns both the per-position logits AND each attention
layer's K/V rows. The engine lands those rows into the paged block
pool (the same ``scatter_blocks`` program preemption-resume uses) and
decode proceeds UNMESHED on the landed blocks — the SP mesh serves
prefill only, so one long-prompt arrival borrows the mesh for one
dispatch instead of sharding the whole server.

Numerics: the ring/Ulysses cores are exact attention evaluated
blockwise (log-sum-exp merges), so logits match the single-device
prefill to float tolerance and temperature-0 first tokens exactly; the
landed K/V rows are projections of the same hidden states — decode
over them is token-exact at temperature 0 (asserted against a
single-device engine in the tests).
"""

from __future__ import annotations

from elephas_tpu.models.transformer import _apply_rope, _rope_tables
from elephas_tpu.serving.kv_cache import _graph_replay, _slice_seq_prefix

__all__ = ["sp_pad_len", "sp_prefill_forward"]


def sp_pad_len(prompt_len: int, sp: int, maxlen: int) -> int | None:
    """Padded sequence length for an SP prefill of ``prompt_len``
    tokens over ``sp`` shards: the smallest power of two covering the
    prompt that also tiles over the shards AND keeps each local shard
    flash-tileable (a power-of-two local length is either ≤128 or a
    multiple of 128, the Pallas kernel's block rule). Returns ``None``
    when no such length fits ``maxlen`` — the caller falls back to the
    single-device path, loudly."""
    s = 1
    while s < max(int(prompt_len), int(sp)):
        s *= 2
    return s if s <= maxlen else None


def sp_prefill_forward(model, w, tokens, mesh, seq_axis: str,
                       mechanism: str, maxlen: int):
    """Full-prompt forward over the SP mesh, K/V captured per layer.

    ``tokens``: ``[1, S]`` int32, ``S`` from :func:`sp_pad_len`
    (padding tokens ride beyond the real prompt — causal attention
    keeps them invisible to every real position, and their K/V rows
    are either truncated by the caller or land past the resident
    cursor where the rewrite-before-visible invariant covers them).

    Returns ``(logits [1, S, vocab], {layer: (k, v)})`` with each
    ``k``/``v`` of shape ``[S, H, Dh]`` — position-major rows ready to
    reshape into pool blocks. Compiled once per padded length ``S``
    (powers of two capped at ``maxlen`` — a closed set)."""
    import jax.numpy as jnp

    from elephas_tpu.ops.ring_attention import ring_attention_sharded
    from elephas_tpu.ops.ulysses import ulysses_attention_sharded

    S = int(tokens.shape[1])
    ctx = {}

    def attn_for(op):
        def attn(x, *_a, **_k):
            H, Dh = op.num_heads, op.head_dim
            B = x.shape[0]  # 1
            qkv = jnp.reshape(
                x @ w[op.qkv.kernel.path], (B, S, 3, H, Dh)
            )
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3,B,H,S,Dh]
            q, k, v = qkv[0], qkv[1], qkv[2]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos = jnp.asarray(cos_np)[None, None, :S]
                sin = jnp.asarray(sin_np)[None, None, :S]
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
            if mechanism == "ulysses":
                o = ulysses_attention_sharded(
                    q, k, v, mesh, axis_name=seq_axis, causal=True,
                    scale=Dh**-0.5,
                )
            else:
                o = ring_attention_sharded(
                    q.reshape(B * H, S, Dh),
                    k.reshape(B * H, S, Dh),
                    v.reshape(B * H, S, Dh),
                    mesh, axis_name=seq_axis, causal=True,
                    scale=Dh**-0.5,
                ).reshape(B, H, S, Dh)
            # position-major K/V rows for the block landing — the same
            # rows single-device prefill would have written
            ctx[op.name] = (
                jnp.transpose(k[0], (1, 0, 2)),  # [S, H, Dh]
                jnp.transpose(v[0], (1, 0, 2)),
            )
            o = jnp.reshape(
                jnp.transpose(o, (0, 2, 1, 3)), (B, S, H * Dh)
            )
            return o @ w[op.proj.kernel.path] + w[op.proj.bias.path]

        return attn

    logits = _graph_replay(
        model, w, tokens, attn_for,
        lambda a: _slice_seq_prefix(a, S, maxlen),
    )
    return logits, ctx
