"""Speculative decoding drafters + acceptance governance (ISSUE 8).

Plain continuous-batching decode advances every slot ONE token per
target-model forward — the serving bench's 1.5-2.6x over sequential is
batching and paging, not per-token speed. Draft-and-verify speculative
decoding (Leviathan et al. 2023) recovers several tokens per forward:
a cheap **drafter** proposes up to K continuation tokens per slot, the
engine scores all of them in ONE batched verify forward (the chunk
programs in :mod:`~elephas_tpu.serving.kv_cache` /
:mod:`~elephas_tpu.serving.paged_kv` — see ``verify_forward`` /
``paged_verify_forward``), and the longest draft prefix matching the
model's own (greedy) tokens is accepted, plus the model's one "bonus"
token from the first non-matching position. At temperature 0 the
accepted tokens are BY CONSTRUCTION the tokens plain decode would have
produced — speculation changes latency, never output.

This module is the host side of that loop:

- :class:`Drafter` — the drafting interface. ``propose(req, k)``
  returns up to ``k`` guessed continuation tokens for one request;
  ``propose_batch`` is the batched entry point the engine calls once
  per verify round (the default fans out to ``propose``; device-backed
  drafters override it to batch their own forwards).
- :class:`NgramDrafter` — prompt-lookup drafting (Saxena 2023):
  matches the request's recent token suffix against its OWN
  prompt+generated history and proposes whatever followed the most
  recent earlier occurrence. Pure host-side string matching — zero
  device cost, and nearly free accuracy on the shared-prefix /
  long-context workloads the prefix cache and paged arena already
  target (templated text keeps repeating itself).
- :class:`DraftModelDrafter` — a second, smaller model from the zoo
  drafts autoregressively in its OWN fixed KV slot arena (one slot per
  engine slot). Catch-up is chunked through one fixed-width program
  and drafting is one greedy multi-step program, so the drafter's
  compiled-shape set is closed like the engine's. The draft arena is
  deliberately fixed (not paged): draft models are small, and the
  drafter's rows are scratch state that is rebuilt from the true token
  stream whenever a slot changes occupants.
- :class:`AcceptanceThrottle` — per-request drafting governor: a
  request whose measured acceptance rate collapses stops drafting
  (falls back to plain decode) and re-probes periodically, so
  adversarial/unpredictable text can never make speculation a
  sustained net loss.

Determinism: drafters run identical host code from identical request
state on every gang process, and the draft model runs unmeshed but
greedy on identical weights — all processes propose identical drafts,
preserving the SPMD contract the engine already imposes.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "Drafter",
    "NgramDrafter",
    "DraftModelDrafter",
    "AcceptanceThrottle",
    "resolve_drafter",
]


class Drafter:
    """Interface: guess the next tokens of a request, cheaply.

    The engine calls :meth:`propose_batch` once per verify round with
    every slot eligible to draft; implementations return ``{slot:
    [token, ...]}`` with at most the per-item ``k`` tokens each. A
    wrong guess costs only wasted verify compute (the acceptance rule
    discards it); a missing guess costs nothing (the slot rides the
    verify round as a plain one-token decode)."""

    # weight generation this drafter's state was built under (ISSUE
    # 20): the engine's refresh_weights() cascade stamps it alongside
    # the re-upload, so a mixed-version fleet debug view can tell a
    # stale draft model from a refreshed one. 0 = unversioned.
    weight_version: int = 0

    def propose(self, req, k: int) -> list[int]:
        """Up to ``k`` guessed continuation tokens for ``req`` (which
        exposes ``prompt``, ``tokens`` and ``full_sequence``). Return
        ``[]`` to skip drafting this round."""
        raise NotImplementedError

    def propose_batch(self, items) -> dict[int, list[int]]:
        """``items`` is ``[(slot, req, k), ...]``; returns ``{slot:
        drafts}``. Default: per-item :meth:`propose` fan-out."""
        return {slot: self.propose(req, k) for slot, req, k in items}

    def refresh_weights(self) -> None:
        """Called by the engine's ``refresh_weights()``: drafters that
        hold model state re-upload it here (the draft model may have
        been retrained alongside the target). Stateless drafters
        no-op."""

    def release(self) -> None:
        """Drop any device/host resources. The engine does not call
        this — its drafter lives (and is garbage-collected) with the
        engine; owners constructing drafters directly may call it to
        free a draft arena early."""


class NgramDrafter(Drafter):
    """Prompt-lookup / n-gram drafting: propose the continuation of
    the most recent earlier occurrence of the request's current token
    suffix inside its own prompt+generated stream.

    Longest suffix first (``max_ngram`` down to ``min_ngram``), most
    recent match first within a suffix length — recency tracks the
    local pattern the sequence is currently in (templated text, code,
    long-context copy tasks). Matching runs over ``full_sequence``, so
    a match may span the prompt/generated boundary, sit entirely in
    the prompt (classic prompt lookup), or entirely in the generated
    tail. No match → no drafts → the slot decodes plainly this round.
    """

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        max_ngram, min_ngram = int(max_ngram), int(min_ngram)
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min_ngram={min_ngram}, max_ngram={max_ngram}"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, req, k: int) -> list[int]:
        seq = req.full_sequence
        n_seq = len(seq)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            # need the suffix PLUS at least one earlier position for a
            # non-trivial match (the terminal occurrence is the query)
            if n_seq < n + 1:
                continue
            suffix = seq[n_seq - n:]
            for i in range(n_seq - n - 1, -1, -1):
                if seq[i:i + n] == suffix:
                    # i + n <= n_seq - 1, so at least one continuation
                    # token always exists
                    return [
                        int(t) for t in seq[i + n: i + n + int(k)]
                    ]
        return []


class DraftModelDrafter(Drafter):
    """Draft with a second (small) causal LM in its own fixed KV slot
    arena — the classic two-model speculative setup.

    The drafter mirrors the engine's slot space: slot ``s`` of the
    draft arena shadows engine slot ``s``. Per :meth:`propose_batch`
    call it (1) **catches up** — feeds the true token stream the
    verify loop has committed since the drafter last saw this slot,
    through one fixed-width chunk program (full prompt on first call
    after an occupant change; the accepted tokens of the last round
    otherwise) — then (2) **drafts** ``k`` tokens greedily with the
    draft model's own single-token decode step, writing scratch K/V
    past the committed frontier. Scratch rows are rewritten by the
    next catch-up before any query can see them (the same
    rewrite-before-visible invariant the engine's verify rollback
    relies on), so no state is ever unwound.

    Occupant changes are self-healing: the drafter keys its committed
    frontier by ``(slot, rid)`` and resets to a full re-ingest when
    the engine reassigns a slot (including preempt/resume moves) —
    no engine hooks required.

    The draft model must share the target's tokenizer space (equal
    vocab) and cover its positions (``draft maxlen >= target
    maxlen``); both are validated loudly. It runs UNMESHED and greedy:
    every gang process derives identical drafts from identical
    weights, keeping the SPMD contract."""

    #: catch-up chunk width — ONE compiled ingest program regardless of
    #: deficit (long prompts loop it); clipped to the draft maxlen
    CATCHUP_CHUNK = 32

    def __init__(self, model, num_slots: int,
                 target_maxlen: int | None = None,
                 target_vocab: int | None = None):
        from elephas_tpu.models.transformer import (
            validate_token_decode_model,
        )
        from elephas_tpu.serving.kv_cache import SlotKVCache

        flash_layers, _stock, _gqa = validate_token_decode_model(
            model,
            what="the draft-model drafter",
            hint="draft with NgramDrafter instead",
            allow_stock=False,
        )
        self.model = model
        self.maxlen = int(model.inputs[0].shape[1])
        self.vocab = int(model.outputs[0].shape[-1])
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots={num_slots} < 1")
        self.validate_for(
            self.num_slots,
            self.maxlen if target_maxlen is None else target_maxlen,
            self.vocab if target_vocab is None else target_vocab,
        )
        self.arena = SlotKVCache(flash_layers, self.num_slots, self.maxlen)
        self._chunk = min(self.CATCHUP_CHUNK, self.maxlen)
        # committed frontier per slot: (rid, tokens of the TRUE stream
        # whose K/V is resident) — scratch draft rows never count
        self._frontier: dict[int, tuple[int, int]] = {}
        self._build()

    def _build(self):
        import jax
        import jax.numpy as jnp

        from elephas_tpu.serving.kv_cache import (
            chunked_prefill_forward,
            token_decode_step,
        )

        model, maxlen = self.model, self.maxlen

        def ingest(w, caches, tokens, offs, clens, act):
            _logits, caches = chunked_prefill_forward(
                model, w, tokens, caches, offs, clens, act, maxlen
            )
            return caches

        def draft(w, caches, last, positions, act, k):
            def body(i, carry):
                caches, last, positions, toks = carry
                pos = jnp.minimum(positions, maxlen - 1)
                logits, caches = token_decode_step(
                    model, w, last, pos, caches, maxlen, active=act
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                toks = toks.at[i].set(nxt)
                return caches, nxt, positions + 1, toks

            toks0 = jnp.zeros((k, last.shape[0]), jnp.int32)
            caches, _last, _pos, toks = jax.lax.fori_loop(
                0, k, body, (caches, last, positions, toks0)
            )
            return caches, toks

        self._ingest_jit = jax.jit(ingest, donate_argnums=(1,))
        self._draft_jit = jax.jit(
            draft, static_argnums=(5,), donate_argnums=(1,)
        )
        self._weights = {
            v.path: jnp.asarray(v.value) for v in model.variables
        }
        self._caches = jax.jit(self.arena.init)()

    def refresh_weights(self) -> None:
        """Re-upload the draft model's weights (after further
        training) and invalidate every committed frontier — resident
        rows were computed under the old weights."""
        import jax.numpy as jnp

        self._weights = {
            v.path: jnp.asarray(v.value) for v in self.model.variables
        }
        self._frontier.clear()

    def propose_batch(self, items) -> dict[int, list[int]]:
        if not items:
            return {}
        seqs = {}
        for slot, req, _k in items:
            seq = req.full_sequence
            seqs[slot] = seq
            rid, seen = self._frontier.get(slot, (None, 0))
            if rid != req.rid:
                seen = 0  # new occupant: full re-ingest
            self._frontier[slot] = (req.rid, seen)
        # -- catch-up: commit the true stream up to (but excluding) the
        # last token — its K/V lands during drafting, exactly the
        # engine's own cursor convention
        while True:
            batch = []
            for slot, req, _k in items:
                rid, seen = self._frontier[slot]
                deficit = len(seqs[slot]) - 1 - seen
                if deficit > 0:
                    batch.append((slot, seen, min(self._chunk, deficit)))
            if not batch:
                break
            rows = np.zeros((self.num_slots, self._chunk), np.int32)
            offs = np.zeros((self.num_slots,), np.int32)
            clens = np.zeros((self.num_slots,), np.int32)
            act = np.zeros((self.num_slots,), bool)
            for slot, seen, take in batch:
                rows[slot, :take] = seqs[slot][seen:seen + take]
                offs[slot] = seen
                clens[slot] = take
                act[slot] = True
            import jax.numpy as jnp

            self._caches = self._ingest_jit(
                self._weights, self._caches, jnp.asarray(rows),
                jnp.asarray(offs), jnp.asarray(clens), jnp.asarray(act),
            )
            for slot, seen, take in batch:
                rid, _seen = self._frontier[slot]
                self._frontier[slot] = (rid, seen + take)
        # -- draft: k greedy tokens from the last true token; rows
        # written past the frontier are scratch (rewritten by the next
        # catch-up before visible)
        k_max = max(int(k) for _s, _r, k in items)
        if k_max < 1:
            return {slot: [] for slot, _r, _k in items}
        import jax.numpy as jnp

        last = np.zeros((self.num_slots,), np.int32)
        positions = np.zeros((self.num_slots,), np.int32)
        act = np.zeros((self.num_slots,), bool)
        for slot, req, k in items:
            if k < 1:
                continue
            last[slot] = seqs[slot][-1]
            positions[slot] = len(seqs[slot]) - 1
            act[slot] = True
        self._caches, toks = self._draft_jit(
            self._weights, self._caches, jnp.asarray(last),
            jnp.asarray(positions), jnp.asarray(act), int(k_max),
        )
        toks = np.asarray(toks)  # [k_max, num_slots]
        return {
            slot: [int(t) for t in toks[: int(k), slot]] if k >= 1 else []
            for slot, _req, k in items
        }

    def validate_for(self, num_slots: int, maxlen: int,
                     vocab: int) -> None:
        """Check this drafter fits a target engine — called by
        ``resolve_drafter`` for PRE-BUILT instances too, so a drafter
        sized for a different engine fails at construction, not with
        an IndexError mid-serve."""
        if self.num_slots < int(num_slots):
            raise ValueError(
                f"draft arena has {self.num_slots} slots but the "
                f"engine serves {num_slots} — the drafter shadows "
                f"engine slots one-to-one"
            )
        if self.maxlen < int(maxlen):
            raise ValueError(
                f"draft model maxlen {self.maxlen} < target maxlen "
                f"{maxlen} — the drafter could not represent "
                f"positions the target decodes at"
            )
        if self.vocab != int(vocab):
            raise ValueError(
                f"draft model vocab {self.vocab} != target vocab "
                f"{vocab} — drafted token ids would not mean the "
                f"same tokens"
            )

    def release(self) -> None:
        self._caches = None
        self._weights = None
        self._frontier.clear()


class AcceptanceThrottle:
    """Per-request drafting governor: measure acceptance over a probe
    window, stop drafting when it collapses, re-probe later.

    A request whose text the drafter cannot predict (adversarial or
    just unpredictable) would otherwise pay draft + K-wide verify
    compute every round for ~1 token — speculation as a net loss.
    The throttle turns that into: draft for ``probe_window`` proposed
    tokens; if the measured acceptance rate is below ``min_rate``,
    stop drafting for ``reprobe_rounds`` decode rounds (the engine
    falls back to plain decode for this request), then probe again —
    text often becomes predictable later (a list, a quote, a repeated
    template). Defaults probe SHORT and back off LONG (8-token window,
    16-round cooldown): a failed probe round costs a full-width verify
    for ~1 token, so the steady-state duty cycle under total collapse
    — ~2 probe rounds per 16 plain — is what bounds the worst-case
    tax. State is plain host bookkeeping keyed by request id;
    telemetry observes it, never drives it."""

    def __init__(self, probe_window: int = 8, min_rate: float = 0.25,
                 reprobe_rounds: int = 16):
        if probe_window < 1:
            raise ValueError(f"probe_window={probe_window} < 1")
        if not 0.0 <= min_rate <= 1.0:
            raise ValueError(f"min_rate={min_rate} outside [0, 1]")
        if reprobe_rounds < 1:
            raise ValueError(f"reprobe_rounds={reprobe_rounds} < 1")
        self.probe_window = int(probe_window)
        self.min_rate = float(min_rate)
        self.reprobe_rounds = int(reprobe_rounds)
        # rid -> [proposed_in_window, accepted_in_window, cooldown]
        self._state: dict[int, list] = {}

    def should_draft(self, rid: int) -> bool:
        """Consult (and advance) the governor for one decode round:
        True = draft this round; False = throttled (the cooldown ticks
        down; hitting zero re-arms a fresh probe window)."""
        st = self._state.setdefault(int(rid), [0, 0, 0])
        if st[2] > 0:
            st[2] -= 1
            if st[2] == 0:
                st[0] = st[1] = 0  # fresh probe window on re-entry
            return False
        return True

    def note(self, rid: int, proposed: int, accepted: int) -> bool:
        """Record one round's outcome; returns True when this round
        TRIPPED the throttle (the caller counts fallbacks)."""
        if proposed <= 0:
            return False
        st = self._state.setdefault(int(rid), [0, 0, 0])
        st[0] += int(proposed)
        st[1] += int(accepted)
        if st[0] >= self.probe_window:
            if st[1] / st[0] < self.min_rate:
                st[2] = self.reprobe_rounds
                return True
            st[0] = st[1] = 0  # healthy: slide the window
        return False

    def throttled(self, rid: int) -> bool:
        st = self._state.get(int(rid))
        return bool(st) and st[2] > 0

    def forget(self, rid: int) -> None:
        """Drop a finished request's state (bounded memory)."""
        self._state.pop(int(rid), None)


def resolve_drafter(spec, num_slots: int, maxlen: int, vocab: int):
    """Engine-side drafter resolution for the ``spec_drafter`` knob:
    ``None``/``"ngram"`` → :class:`NgramDrafter`; a :class:`Drafter`
    instance passes through; a causal-LM keras model wraps into a
    :class:`DraftModelDrafter` sized to the engine. Anything else is
    rejected loudly."""
    if spec is None or (isinstance(spec, str) and spec == "ngram"):
        return NgramDrafter()
    if isinstance(spec, DraftModelDrafter):
        # a pre-built instance may have been sized for a DIFFERENT
        # engine: fail here, not with an IndexError mid-serve
        spec.validate_for(num_slots, maxlen, vocab)
        return spec
    if isinstance(spec, Drafter):
        return spec
    if hasattr(spec, "inputs") and hasattr(spec, "outputs"):
        return DraftModelDrafter(
            spec, num_slots=num_slots,
            target_maxlen=maxlen, target_vocab=vocab,
        )
    raise ValueError(
        f"spec_drafter={spec!r} is not a drafter: pass 'ngram', a "
        f"serving.Drafter instance, or a causal-LM keras model to "
        f"draft with"
    )
