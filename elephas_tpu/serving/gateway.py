"""Async HTTP/SSE front door over the serving engine (ISSUE 10).

Before this module the only way into :class:`~elephas_tpu.serving.\
engine.InferenceEngine` was an in-process ``submit()`` — fine for a
notebook, useless for the "millions of users" north star. The
:class:`Gateway` puts one wire in front of one engine:

- ``POST /v1/generate`` — JSON body ``{"prompt": [ints],
  "max_new_tokens": n, "temperature": t, "eos_id": e, "tenant": name,
  "ttft_deadline_ms": ms, "stream": true}``. With ``stream`` (the
  default) the response is Server-Sent Events riding the engine's
  per-request ``on_token`` callback (the PR-3 streaming hook): one
  ``data: {"token": t, "done": d}`` event per generated token after an
  opening ``data: {"rid": id}`` event, then the connection closes.
  ``stream: false`` buffers and returns one JSON document.
  The BATCH form (ISSUE 15) carries ``"prompts": [[...], ...]``
  instead of ``prompt``: every prompt is a normal ``submit()`` (the
  policy and admission control judge each individually), answered as
  one ``results`` JSON array or one rid-multiplexed SSE stream.
- **HTTP keep-alive** (ISSUE 15 — the other half of ROADMAP item 2's
  wire hardening): a ``Connection: keep-alive`` client (HTTP/1.1
  default) gets its next request served off the same socket under a
  bounded idle timeout (``keepalive_idle_timeout``, default 5s);
  reuse is counted in ``elephas_gateway_connections_reused_total``.
  SSE responses still own their connection to the end.
- ``GET /metrics`` — the process registry through the PR-5 Prometheus
  renderer (the same text an in-process ``engine.scrape()`` returns);
  an ``Accept: application/openmetrics-text`` client gets the
  OpenMetrics flavor with rid-stamped histogram exemplars (ISSUE 12).
- ``GET /stats`` — ``engine.stats()`` as JSON (per-tenant SLO section
  included).
- ``GET /healthz`` — cheap liveness for a fleet router (ISSUE 12):
  200 while the driver thread is alive and steps advance when there
  is work, 503 otherwise. Never waits on the engine lock.
- ``GET /v1/requests/{rid}/trace`` — the flight-recorder lifecycle
  record of one request (``engine.explain(rid)`` on the wire).
- ``GET /debug/engine`` — ``engine.debug_snapshot()`` as JSON: slot
  map, waiting queue with policy debt, block-pool occupancy, prefix
  index summary, compile stats.

Every ``/v1/generate`` response — SSE, buffered JSON, and the 429/422
rejects alike — echoes the engine-minted request id as an
``X-Request-Id`` header (and in the SSE opening event / JSON body),
so a client, proxy log, or exemplar-following dashboard can join any
response to its trace.

Backpressure is the policy's admission verdict on the wire: a submit
refused by overload admission control returns **429** with a
``Retry-After`` header carrying the policy's deterministic hint —
the gateway never buffers a request the scheduler already refused.
Validation errors return 400 with the ValueError's message; the
engine's graceful paged never-fit rejection returns 422 (the request
can NEVER be served at this configuration — retrying is pointless,
which is exactly what distinguishes it from the 429).

Connection hygiene applies the ``utils/sockets.py`` lessons rather
than growing a second ad-hoc transport stack: every read sits under a
deadline (a half-open socket cannot pin a handler), every write goes
through ``drain()`` (short-write safety under client backpressure),
and :meth:`Gateway.stop` **severs live SSE connections** and releases
the port — the same zombie keep-alive bug class PR 3 found in the
parameter servers, fixed here by construction and pinned by a test
that rebinds the port.

Threading model: the asyncio loop runs in one daemon thread (socket
I/O only — it never touches jax), a driver thread steps the engine
whenever the scheduler has work, and a single lock serializes
``submit()``/``step()`` on the engine (host bookkeeping; the device
programs themselves are dispatched only from the driver thread).
Tokens cross from the driver thread into the loop via
``call_soon_threadsafe`` onto per-request asyncio queues.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import threading
import time

from elephas_tpu import telemetry
from elephas_tpu.serving.policy import AdmissionRejected

logger = logging.getLogger(__name__)

#: Read deadline for request line / headers / body — a dead or
#: dribbling client is cut loose instead of pinning a handler task
#: (sockets.py: connections get deadlines, period).
READ_TIMEOUT = 30.0
#: Largest accepted request body; a prompt is a list of ints, so even
#: maxlen-scale prompts sit far below this.
MAX_BODY = 1 << 20

_STATUS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 422: "Unprocessable Entity",
    429: "Too Many Requests", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}


def _response(code: int, body: bytes, content_type: str,
              extra_headers=(), close: bool = True) -> bytes:
    head = [
        f"HTTP/1.1 {code} {_STATUS.get(code, 'Unknown')}",
        f"Content-Type: {content_type}",
        f"Content-Length: {len(body)}",
        "Connection: close" if close else "Connection: keep-alive",
    ]
    head.extend(f"{k}: {v}" for k, v in extra_headers)
    return ("\r\n".join(head) + "\r\n\r\n").encode("ascii") + body


def _json_response(code: int, obj, extra_headers=(),
                   close: bool = True) -> bytes:
    return _response(
        code, json.dumps(obj).encode("utf-8") + b"\n",
        "application/json", extra_headers, close=close,
    )


class _Conn:
    """Per-connection keep-alive state (ISSUE 15 satellite): whether
    the CURRENT response may leave the connection open. Handlers that
    must own the socket to its end (SSE streams) flip ``persist``
    off; everything else answers ``Connection: keep-alive`` when the
    client asked for it and reads the next request off the same
    socket under a bounded idle timeout."""

    __slots__ = ("persist", "served")

    def __init__(self):
        self.persist = False
        self.served = 0

    def close_header(self) -> bool:
        return not self.persist


class _ConnectionClosed(Exception):
    """EOF where a request line should start — a 400 on a fresh
    connection, a clean goodbye on an idle keep-alive one."""


class _HttpError(Exception):
    """Maps straight to one non-200 response."""

    def __init__(self, code: int, message: str, extra_headers=()):
        super().__init__(message)
        self.code = code
        self.extra_headers = tuple(extra_headers)


class Gateway:
    """One HTTP/SSE front door over one engine. ``port=0`` binds an
    ephemeral port (read :attr:`port` after :meth:`start`). Use as a
    context manager, or pair :meth:`start`/:meth:`stop` — stop severs
    live SSE connections and releases the port."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 read_timeout: float = READ_TIMEOUT,
                 max_body: int = MAX_BODY,
                 max_migrate_body: int = 1 << 28,
                 health_stall_grace: float = 120.0,
                 keepalive_idle_timeout: float = 5.0,
                 max_batch_prompts: int = 64,
                 watchdog=None):
        self.engine = engine
        self.host = host
        self._want_port = int(port)
        self.port: int | None = None
        self.read_timeout = float(read_timeout)
        self.max_body = int(max_body)
        # HTTP keep-alive (ISSUE 15 satellite — ROADMAP item 2): a
        # client that asks for it (HTTP/1.1 default) gets its next
        # request served off the SAME connection, bounded by this idle
        # timeout between requests (0 disables persistence outright).
        # SSE streams still own their socket to the end.
        self.keepalive_idle_timeout = float(keepalive_idle_timeout)
        # /v1/generate batch form: one POST may carry up to this many
        # prompts (each a NORMAL submit — policy/admission see them
        # individually); bounded so a single request cannot flood the
        # queue past what admission control can see coming
        self.max_batch_prompts = int(max_batch_prompts)
        # migration records carry dense K/V blocks — orders of
        # magnitude bigger than a generate body; own bound (ISSUE 14)
        self.max_migrate_body = int(max_migrate_body)
        # /healthz stall detection (ISSUE 12): grace window before
        # "has work but steps are not advancing" reports 503. A
        # first-request XLA compile legitimately freezes steps for a
        # while, so the default is generous (2 min); size the knob to
        # your model's cold-start compile time — a router probing a
        # large model with a tight grace WILL false-positive during
        # warmup
        self.health_stall_grace = float(health_stall_grace)
        # (steps, monotonic-time) of the last observed step progress;
        # time.monotonic is a LOCAL duration clock — wall clock stays
        # banned on serving control paths (telemetry lint)
        self._hz_anchor: tuple[int, float] | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server: asyncio.AbstractServer | None = None
        self._loop_thread: threading.Thread | None = None
        self._driver_thread: threading.Thread | None = None
        # serializes engine.submit() (loop thread) vs engine.step()
        # (driver thread) — both are host bookkeeping; device dispatch
        # stays on the driver side of this lock
        self._engine_lock = threading.Lock()
        self._work = threading.Event()
        self._stopping = threading.Event()
        # _stopping means "no new work" (the driver's crash path sets
        # it too); _stopped is the one-shot teardown latch — stop()
        # must still run its full teardown after a driver crash, or
        # the port and live handlers would leak exactly the way the
        # module docstring promises they cannot
        self._stopped = False
        self._stop_lock = threading.Lock()
        self._started = False
        # live handler tasks + writers, so stop() can sever them
        self._tasks: set[asyncio.Task] = set()
        self._writers: set[asyncio.StreamWriter] = set()
        # telemetry (engine-label family set: release_telemetry on the
        # gateway retires only its own series)
        reg = telemetry.registry()
        self._tracer = telemetry.tracer()
        gid = telemetry.instance_label()
        self.telemetry_label = gid
        self._m_requests = reg.counter(
            "elephas_gateway_requests_total",
            "HTTP requests served by the gateway, by route and status",
            labels=("gateway", "route", "code"),
        )
        self._m_sse_active = reg.gauge(
            "elephas_gateway_sse_active",
            "SSE token streams currently open",
            labels=("gateway",),
        ).labels(gateway=gid)
        self._m_conn_reused = reg.counter(
            "elephas_gateway_connections_reused_total",
            "Requests served off an already-open keep-alive "
            "connection (the handshake they did not pay)",
            labels=("gateway",),
        ).labels(gateway=gid)
        # anomaly watchdog (ISSUE 13): rules evaluate at /healthz
        # PROBE cadence — never per step/token, the hot-path contract
        # — and the report embeds as healthz detail so a fleet router
        # reads liveness AND the why in one probe. None under null
        # mode (inert by construction); pass watchdog=False to opt
        # out, or a prebuilt Watchdog (e.g. with tuned rules).
        from elephas_tpu.telemetry.watch import Watchdog

        if watchdog is None:
            watchdog = (
                Watchdog() if not telemetry.null_mode() else None
            )
        elif watchdog is False or watchdog == 0:
            watchdog = None
        elif not isinstance(watchdog, Watchdog):
            raise TypeError(
                f"watchdog must be a telemetry.watch.Watchdog, None "
                f"(auto), or False (off), got "
                f"{type(watchdog).__name__}"
            )
        self.watchdog = watchdog

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Gateway":
        if self._started:
            raise RuntimeError("gateway already started")
        self._started = True
        ready = threading.Event()
        boot_err: list[BaseException] = []

        def loop_main():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                self._server = loop.run_until_complete(
                    asyncio.start_server(
                        self._handle, self.host, self._want_port
                    )
                )
            except OSError as e:  # port in use, bad host, ...
                boot_err.append(e)
                loop.close()  # else the selector fd leaks until GC
                ready.set()
                return
            self.port = self._server.sockets[0].getsockname()[1]
            ready.set()
            try:
                loop.run_forever()
            finally:
                # loop.stop() ran inside _shutdown(); the server and
                # every transport are already closed there
                loop.close()

        self._loop_thread = threading.Thread(
            target=loop_main, name="gateway-loop", daemon=True
        )
        self._loop_thread.start()
        ready.wait()
        if boot_err:
            self._started = False
            raise boot_err[0]
        self._driver_thread = threading.Thread(
            target=self._drive, name="gateway-driver", daemon=True
        )
        self._driver_thread.start()
        logger.info(
            "gateway listening on %s:%d (engine %s)",
            self.host, self.port, self.engine.telemetry_label,
        )
        return self

    def stop(self) -> None:
        """Sever everything: stop the driver, close the listener and
        EVERY live connection (SSE streams included), stop the loop,
        join both threads, release the port. Idempotent — and runs
        its full teardown even when the driver already crashed (the
        crash path only flags ``_stopping``; this is the half that
        actually releases the port)."""
        if not self._started:
            return
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._stopping.set()
        self._work.set()  # wake the driver so it can observe stopping
        dt = self._driver_thread
        if dt is not None and dt is not threading.current_thread():
            dt.join(timeout=30)
        loop = self._loop
        if loop is not None and not loop.is_closed():
            done = threading.Event()
            loop.call_soon_threadsafe(
                lambda: loop.create_task(self._shutdown(done))
            )
            done.wait(timeout=30)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=30)
        logger.info("gateway on port %s stopped", self.port)

    async def _shutdown(self, done: threading.Event) -> None:
        loop = asyncio.get_running_loop()
        try:
            if self._server is not None:
                self._server.close()
                await self._server.wait_closed()
            # sever live SSE connections — the zombie keep-alive bug
            # class (PR 3, parameter servers): a handler mid-stream
            # must not outlive the gateway
            for w in list(self._writers):
                try:
                    w.close()
                except OSError:
                    pass  # fault-lint: allow — already-dead transport
            for t in list(self._tasks):
                t.cancel()
            if self._tasks:
                await asyncio.gather(
                    *list(self._tasks), return_exceptions=True
                )
        finally:
            done.set()
            loop.stop()

    def release_telemetry(self) -> None:
        """Retire this gateway's labeled series (explicit-only, same
        contract as the engine's)."""
        telemetry.remove_series(gateway=self.telemetry_label)

    def __enter__(self) -> "Gateway":
        return self.start() if not self._started else self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    # -- engine driver --------------------------------------------------

    def _drive(self) -> None:
        """Step the engine whenever the scheduler has work; park on an
        event otherwise (a submit sets it). Any engine error severs the
        gateway LOUDLY — serving garbage quietly is the one thing a
        front door must never do."""
        try:
            while not self._stopping.is_set():
                with self._engine_lock:
                    has_work = self.engine.scheduler.has_work
                    if has_work:
                        self.engine.step()
                if not has_work:
                    self._work.wait(timeout=0.05)
                    self._work.clear()
        except Exception:
            logger.exception(
                "gateway driver died mid-step — severing the gateway "
                "(in-flight streams will close)"
            )
            # run the REAL teardown, not just the flag: in-flight
            # handlers are parked on queues no tokens will ever reach
            # again, and the port must come back. stop() skips joining
            # the current (driver) thread.
            self.stop()

    # -- request handling (loop thread) ---------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._tasks.add(task)
        self._writers.add(writer)
        conn = _Conn()
        try:
            # keep-alive request loop (ISSUE 15 satellite): one
            # connection may carry many requests; the first read sits
            # under the full read deadline, subsequent ones under the
            # bounded IDLE timeout (an open-but-silent keep-alive
            # socket must not pin a handler task forever)
            while await self._serve_one(reader, writer, conn):
                conn.served += 1
        except (ConnectionError, OSError) as e:
            logger.info("gateway connection dropped (%r)", e)
        except asyncio.CancelledError:
            # stop() severing us — close fast, propagate nothing
            pass  # fault-lint: allow — deliberate sever on stop()
        except Exception:
            logger.exception("gateway handler failed")
        finally:
            self._writers.discard(writer)
            self._tasks.discard(task)
            try:
                writer.close()
            except OSError:
                pass  # fault-lint: allow — already-severed transport

    async def _serve_one(self, reader, writer, conn: _Conn) -> bool:
        """Read and answer ONE request off the connection. Returns
        True when the connection persists for another request (client
        asked for keep-alive, the response could honor it, and the
        gateway is not stopping)."""
        route, code = "other", None
        first = conn.served == 0
        try:
            try:
                if first:
                    # ONE deadline over the whole request read: the
                    # per-line timeouts inside cannot bound a client
                    # that dribbles a header every few seconds forever
                    (method, path, body, headers,
                     version) = await asyncio.wait_for(
                        self._read_request(reader), self.read_timeout
                    )
                else:
                    # the idle timeout governs only the WAIT for the
                    # next request LINE; once bytes arrive the full
                    # read deadline takes over (a large migrate body
                    # on a reused connection must not race the short
                    # idle clock)
                    try:
                        line = await asyncio.wait_for(
                            reader.readline(),
                            min(self.read_timeout,
                                self.keepalive_idle_timeout),
                        )
                        # RFC 7230 §3.5: ignore blank line(s) before
                        # the next request line (bounded — a blank
                        # flood must not pin the handler)
                        skipped = 0
                        while line in (b"\r\n", b"\n") and skipped < 4:
                            skipped += 1
                            line = await asyncio.wait_for(
                                reader.readline(),
                                min(self.read_timeout,
                                    self.keepalive_idle_timeout),
                            )
                    except asyncio.TimeoutError:
                        return False  # idle expiry: just close
                    if not line or line in (b"\r\n", b"\n"):
                        return False  # clean close between requests
                    # this request rode an already-open connection —
                    # the handshake it did not pay (ISSUE 15)
                    self._m_conn_reused.inc()
                    (method, path, body, headers,
                     version) = await asyncio.wait_for(
                        self._read_request(reader, first_line=line),
                        self.read_timeout,
                    )
            except _ConnectionClosed:
                if first:
                    code = 400
                    await self._write(writer, _json_response(
                        400, {"error": "empty request"}
                    ))
                return False
            except _HttpError as e:
                # a read-side refusal (malformed line, oversized or
                # chunked body) still gets its response — and always
                # closes: the connection's framing cannot be trusted
                # past a failed read
                code = e.code
                await self._write(writer, _json_response(
                    e.code, {"error": str(e)}, e.extra_headers
                ))
                return False
            except asyncio.TimeoutError:
                code = 408
                await self._write(writer, _json_response(
                    408, {"error": "request read timed out"}
                ))
                return False
            try:
                conn_hdr = headers.get("connection", "").lower()
                conn.persist = (
                    self.keepalive_idle_timeout > 0
                    and "close" not in conn_hdr
                    and (version == "HTTP/1.1"
                         or "keep-alive" in conn_hdr)
                    and not self._stopping.is_set()
                )
                route = self._route_label(method, path)
                # gateway label + (for /v1/generate, set below) the
                # engine-minted rid ride the span args: the trace-merge
                # tool (ISSUE 13) keys the request's trace id off the
                # rid, so the gateway half of the story joins the
                # engine half under ONE id on the merged timeline
                with self._tracer.span(
                    "gateway.request", route=route,
                    gateway=self.telemetry_label,
                ) as span:
                    code = await self._route(
                        method, path, body, headers, writer, span,
                        conn,
                    )
            except _HttpError as e:
                code = e.code
                await self._write(writer, _json_response(
                    e.code, {"error": str(e)}, e.extra_headers,
                    close=conn.close_header(),
                ))
            except Exception:
                # an unexpected handler failure must still land in the
                # request metric as a 500 before _handle logs it and
                # severs the connection — a fleet watching the 5xx
                # rate cannot be blind to crashing handlers
                code = 500
                raise
        finally:
            if code is not None:
                self._m_requests.labels(
                    gateway=self.telemetry_label, route=route,
                    code=str(code),
                ).inc()
        return conn.persist

    _TRACE_PATH = re.compile(r"^/v1/requests/(\d+)/trace$")
    _CANCEL_PATH = re.compile(r"^/v1/requests/(\d+)/cancel$")
    _EXPORT_PATH = re.compile(r"^/v1/requests/(\d+)/export$")

    @classmethod
    def _route_label(cls, method: str, path: str) -> str:
        """Metric label for the route — KNOWN (method, path) pairs
        only, everything else collapses to "other": no part of the
        label value may be client-controlled, or a scanner walking
        paths (or inventing METHOD tokens on real paths) mints
        unbounded registry series. The per-request trace route
        collapses its rid into the ``:rid`` template for the same
        reason."""
        bare = path.split("?", 1)[0]
        if method == "GET" and cls._TRACE_PATH.match(bare):
            return "GET /v1/requests/:rid/trace"
        if method == "POST" and cls._CANCEL_PATH.match(bare):
            return "POST /v1/requests/:rid/cancel"
        if method == "POST" and cls._EXPORT_PATH.match(bare):
            return "POST /v1/requests/:rid/export"
        route = f"{method} {bare}"
        if route in (
            "POST /v1/generate", "POST /v1/score", "GET /metrics",
            "GET /stats", "GET /healthz", "GET /debug/engine",
            "POST /v1/migrate",
        ):
            return route
        return "other"

    async def _read_request(self, reader, first_line=None):
        # no per-read deadlines here: the caller wraps this WHOLE
        # coroutine in one wait_for(read_timeout), which is the bound
        # that actually governs (per-line timeouts could never cut a
        # client dribbling one header per interval loose).
        # ``first_line`` — a request line the keep-alive loop already
        # read under the idle timeout.
        line = first_line
        if line is None:
            line = await reader.readline()
        if not line:
            raise _ConnectionClosed()
        try:
            method, path, version = line.decode("ascii").split()
        except ValueError:
            raise _HttpError(400, f"malformed request line {line!r}")
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= 128:
                raise _HttpError(400, "too many headers")
            if b":" in h:
                k, v = h.split(b":", 1)
                headers[k.strip().lower().decode("ascii")] = (
                    v.strip().decode("latin-1")
                )
        body = b""
        if "transfer-encoding" in headers:
            # bodies arrive via Content-Length ONLY. Silently reading
            # a 0-byte body under keep-alive would leave the chunked
            # payload buffered on the socket and parse it as the NEXT
            # request line — attacker-controlled request smuggling
            # behind any validating front proxy. Refuse, and the
            # caller closes (framing past this point is untrusted).
            raise _HttpError(
                501, "Transfer-Encoding is not supported — send a "
                     "Content-Length body"
            )
        try:
            n = int(headers.get("content-length", "0"))
        except ValueError:
            raise _HttpError(400, "bad Content-Length")
        limit = (
            self.max_migrate_body
            if path.split("?", 1)[0] == "/v1/migrate"
            else self.max_body
        )
        if n > limit:
            raise _HttpError(
                413, f"body of {n} bytes exceeds {limit}"
            )
        if n:
            # consume the declared body for EVERY method: a GET with
            # a Content-Length body left unread would desync the
            # keep-alive framing — the body bytes would parse as the
            # next request line (same smuggling class as the
            # Transfer-Encoding refusal above)
            body = await reader.readexactly(n)
        return method, path, body, headers, version

    async def _write(self, writer, data: bytes) -> None:
        # sockets.py lesson: sendall/drain after every write — a slow
        # consumer backpressures the handler, never silently truncates
        writer.write(data)
        await writer.drain()

    async def _route(self, method, path, body, headers, writer,
                     span=None, conn=None) -> int:
        if conn is None:
            conn = _Conn()
        path = path.split("?", 1)[0]
        if path == "/v1/generate":
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._generate(body, writer, span, conn)
        if path == "/metrics":
            if method != "GET":
                raise _HttpError(405, "GET only")
            # content negotiation (ISSUE 12): an OpenMetrics-aware
            # scraper gets histogram exemplars (rid-stamped TTFT/ITL
            # observations); the 0.0.4 default stays exemplar-free
            # because its parsers reject a '#' after the value
            if _wants_openmetrics(headers.get("accept", "")):
                text = telemetry.render_openmetrics().encode("utf-8")
                ctype = telemetry.CONTENT_TYPE_OPENMETRICS
            else:
                text = telemetry.render().encode("utf-8")
                ctype = telemetry.CONTENT_TYPE
            await self._write(writer, _response(
                200, text, ctype, close=conn.close_header()
            ))
            return 200
        if path == "/stats":
            if method != "GET":
                raise _HttpError(405, "GET only")
            return await self._json_snapshot(
                writer, lambda: self.engine.stats(), conn
            )
        if path == "/healthz":
            if method != "GET":
                raise _HttpError(405, "GET only")
            return await self._healthz(writer, conn)
        if path == "/debug/engine":
            if method != "GET":
                raise _HttpError(405, "GET only")
            return await self._json_snapshot(
                writer, lambda: self.engine.debug_snapshot(), conn
            )
        m = self._TRACE_PATH.match(path)
        if m is not None:
            if method != "GET":
                raise _HttpError(405, "GET only")
            return await self._request_trace(
                int(m.group(1)), writer, conn
            )
        m = self._CANCEL_PATH.match(path)
        if m is not None:
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._cancel(int(m.group(1)), writer, conn)
        m = self._EXPORT_PATH.match(path)
        if m is not None:
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._export(int(m.group(1)), writer, conn)
        if path == "/v1/migrate":
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._migrate(body, writer, conn)
        if path == "/v1/score":
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._score(body, writer, conn)
        raise _HttpError(404, f"no route {path}")

    async def _score(self, body: bytes, writer, conn) -> int:
        """``POST /v1/score`` — log-probabilities of a given completion
        under the served model in ONE forward pass (ISSUE 19): body is
        ``{"prompt": [tokens], "completion": [tokens]}``, response
        carries per-token logprobs, their sum, the greedy (argmax)
        token at each position, and the completion-vs-greedy agreement
        fraction. This is the quality oracle the quant bench gates
        consume: score the same completion on an fp and a quantized
        engine and compare. Scoring never perturbs in-flight serving
        state (the engine forward is discard-after-read), but it DOES
        take the engine lock for its forward, like any submit."""
        try:
            spec = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _HttpError(400, f"bad JSON body: {e}")
        if not isinstance(spec, dict):
            raise _HttpError(400, "body must be a JSON object")
        unknown = set(spec) - {"prompt", "completion"}
        if unknown:
            raise _HttpError(400, f"unknown fields {sorted(unknown)}")
        for key in ("prompt", "completion"):
            if not isinstance(spec.get(key), list):
                raise _HttpError(
                    400, f"{key} must be a list of token ids"
                )
        loop = asyncio.get_running_loop()

        def do_score():
            with self._engine_lock:
                if self._stopping.is_set():
                    raise _HttpError(503, "gateway is stopping")
                return self.engine.score(
                    spec["prompt"], spec["completion"]
                )

        try:
            result = await loop.run_in_executor(None, do_score)
        except (ValueError, TypeError) as e:
            raise _HttpError(400, str(e))
        await self._write(writer, _json_response(
            200, result, close=conn.close_header(),
        ))
        return 200

    async def _cancel(self, rid: int, writer, conn) -> int:
        """``POST /v1/requests/{rid}/cancel`` — abort one in-flight
        request and reclaim its slot/blocks (ISSUE 14). 404 when the
        rid is unknown or already finished (nothing to reclaim)."""
        loop = asyncio.get_running_loop()

        def do_cancel():
            with self._engine_lock:
                return self.engine.cancel(rid)

        if not await loop.run_in_executor(None, do_cancel):
            raise _HttpError(
                404, f"request {rid} is not in flight on this engine"
            )
        await self._write(writer, _json_response(
            200, {"rid": rid, "cancelled": True},
            extra_headers=(("X-Request-Id", str(rid)),),
            close=conn.close_header(),
        ))
        return 200

    async def _export(self, rid: int, writer, conn) -> int:
        """``POST /v1/requests/{rid}/export`` — freeze one live
        request and return its migration record as the v1 binary wire
        format (ISSUE 14): the request LEAVES this engine; POST the
        bytes to another replica's ``/v1/migrate`` to resume it
        there. 404 for a rid that is not live here, 409 when the
        request cannot be exported (fixed-arena warm export)."""
        from elephas_tpu.fleet.migration import encode_record

        loop = asyncio.get_running_loop()

        def do_export():
            with self._engine_lock:
                # notify_stream: the request leaves THIS engine for
                # good over the wire — a local SSE/JSON handler
                # blocking on its token stream must end, not hang
                record = self.engine.export_request(
                    rid, notify_stream=True
                )
            # the encode is pure host work over an already-detached
            # record — serializing potentially hundreds of MB of K/V
            # rows must not stall the decode driver behind the lock
            return encode_record(record)

        try:
            payload = await loop.run_in_executor(None, do_export)
        except KeyError as e:
            raise _HttpError(404, str(e).strip("'\""))
        except ValueError as e:
            raise _HttpError(409, str(e))
        await self._write(writer, _response(
            200, payload, "application/octet-stream",
            extra_headers=(("X-Request-Id", str(rid)),),
            close=conn.close_header(),
        ))
        return 200

    async def _migrate(self, body: bytes, writer, conn) -> int:
        """``POST /v1/migrate`` — adopt a migration record exported by
        another replica (the drain/rebalance wire, ISSUE 14). The body
        is the v1 binary record; the response confirms the adopted rid
        and whether the K/V resumed warm. No token stream re-attaches
        over this route (callbacks never travel) — the in-process
        fleet router re-wires streams itself; a wire-migrated request
        accumulates tokens readable via its trace/stats surfaces.

        Passthrough validation (ISSUE 20): the record's ``weight_ver``
        rides the decoded header into ``import_request``, whose
        generation-mismatch refusal surfaces here as 409 — a warm
        record from another weight generation must never resume as
        silent garbage over the wire either."""
        from elephas_tpu.fleet.migration import decode_record

        loop = asyncio.get_running_loop()

        def do_import():
            record = decode_record(body)
            with self._engine_lock:
                req = self.engine.import_request(record)
                return req.rid, int(record.get("n_blocks") or 0) > 0

        try:
            rid, warm = await loop.run_in_executor(None, do_import)
        except ValueError as e:
            raise _HttpError(409, str(e))
        self._work.set()  # wake the driver: the adoptee needs steps
        await self._write(writer, _json_response(
            200, {"rid": rid, "warm": warm},
            extra_headers=(("X-Request-Id", str(rid)),),
            close=conn.close_header(),
        ))
        return 200

    async def _json_snapshot(self, writer, fn, conn) -> int:
        """Serve ``fn()`` (engine introspection under the engine lock)
        as one JSON document, computed off-loop: the lock may be held
        by a long engine step and must not freeze the event loop."""
        loop = asyncio.get_running_loop()

        def snapshot():
            with self._engine_lock:
                return json.dumps(
                    fn(), default=float
                ).encode("utf-8") + b"\n"

        body = await loop.run_in_executor(None, snapshot)
        await self._write(writer, _response(
            200, body, "application/json",
            close=conn.close_header(),
        ))
        return 200

    async def _request_trace(self, rid: int, writer, conn) -> int:
        """``GET /v1/requests/{rid}/trace`` — the engine's flight-
        recorder record for one request (ISSUE 12). 404 for an
        unknown/evicted rid, 501 when the recorder is off (retrying
        cannot help; the engine must be rebuilt with
        ``flight_recorder=N``)."""
        loop = asyncio.get_running_loop()

        def lookup():
            with self._engine_lock:
                return self.engine.explain(rid)

        try:
            record = await loop.run_in_executor(None, lookup)
        except KeyError as e:
            raise _HttpError(404, str(e).strip("'\""))
        except RuntimeError as e:
            raise _HttpError(501, str(e))
        await self._write(writer, _json_response(
            200, record,
            extra_headers=(("X-Request-Id", str(rid)),),
            close=conn.close_header(),
        ))
        return 200

    async def _healthz(self, writer, conn) -> int:
        """Cheap liveness for the fleet router (ISSUE 12 satellite):
        200 when the engine driver thread is alive, the gateway is not
        stopping, and — when there is work — steps are advancing;
        answering at all proves the event loop responsive. Reads a
        couple of ints without the engine lock (GIL-atomic loads): a
        health probe must never queue behind a long step."""
        driver = self._driver_thread
        alive = (
            driver is not None and driver.is_alive()
            and not self._stopping.is_set()
        )
        sched = self.engine.scheduler
        steps = sched._steps
        has_work = sched.has_work
        now = time.monotonic()
        anchor = self._hz_anchor
        if not has_work or anchor is None or anchor[0] != steps:
            self._hz_anchor = anchor = (steps, now)
        stalled = (
            has_work and now - anchor[1] > self.health_stall_grace
        )
        status = (
            "driver-dead" if not alive
            else "stalled" if stalled else "ok"
        )
        from elephas_tpu.utils import backend_guard

        body = {
            "status": status,
            "steps": steps,
            "queue_has_work": has_work,
            "driver_alive": alive,
            # ISSUE 20: the weight generation this replica serves — a
            # GIL-atomic int read, so a mixed-version fleet is visible
            # from health probes alone (report-only, never flips the
            # verdict: an old generation is stale, not dead)
            "weight_version": self.engine.weight_version,
            # ISSUE 19 satellite: if jax backend discovery fell back
            # to CPU (the BENCH_r05 driver-box TPU init crash), every
            # health probe says so — report-only, never flips the
            # 200/503 verdict (a CPU engine is slow, not dead)
            "backend_fallback": backend_guard.last_fallback(),
        }
        if self.watchdog is not None:
            # anomaly detail (ISSUE 13): evaluated HERE, at probe
            # cadence. Report-only — anomalies never flip the 200/503
            # verdict (that would let telemetry drive routing; the
            # stall/driver checks above are the liveness authority) —
            # but the router gets the why alongside the what.
            # Off-loop like every other registry walk: evaluation
            # reads pull-time callback gauges whose cost grows with
            # tenants/series, and a probe must never stall in-flight
            # SSE streams (the handler's own never-block design).
            loop = asyncio.get_running_loop()

            def evaluate():
                self.watchdog.evaluate()
                return self.watchdog.report()

            report = await loop.run_in_executor(None, evaluate)
            body["anomalies"] = {
                "critical": report["critical"],
                "warn": report["warn"],
                "active": report["active"],
            }
        await self._write(writer, _json_response(
            200 if status == "ok" else 503, body,
            close=conn.close_header(),
        ))
        return 200 if status == "ok" else 503

    def _parse_generate(self, body: bytes) -> dict:
        try:
            spec = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _HttpError(400, f"bad JSON body: {e}")
        if not isinstance(spec, dict):
            raise _HttpError(400, "body must be a JSON object")
        unknown = set(spec) - {
            "prompt", "prompts", "max_new_tokens", "temperature",
            "eos_id", "tenant", "ttft_deadline_ms", "priority",
            "stream",
        }
        if unknown:
            raise _HttpError(400, f"unknown fields {sorted(unknown)}")
        if ("prompt" in spec) == ("prompts" in spec):
            raise _HttpError(
                400, "exactly one of prompt / prompts is required"
            )
        if "max_new_tokens" not in spec:
            raise _HttpError(
                400, "prompt and max_new_tokens are required"
            )
        if "prompts" in spec:
            prompts = spec["prompts"]
            if not isinstance(prompts, list) or not prompts or not all(
                isinstance(p, list) for p in prompts
            ):
                raise _HttpError(
                    400, "prompts must be a non-empty list of "
                         "token lists"
                )
            if len(prompts) > self.max_batch_prompts:
                raise _HttpError(
                    413,
                    f"{len(prompts)} prompts exceed the batch bound "
                    f"{self.max_batch_prompts} — split the POST",
                )
        return spec

    def _submit_kwargs(self, spec) -> dict:
        return dict(
            temperature=float(spec.get("temperature", 0.0)),
            eos_id=spec.get("eos_id"),
            tenant=spec.get("tenant"),
            ttft_deadline_ms=spec.get("ttft_deadline_ms"),
            priority=int(spec.get("priority", 0)),
        )

    async def _generate(self, body, writer, span=None,
                        conn=None) -> int:
        if conn is None:
            conn = _Conn()
        spec = self._parse_generate(body)
        if "prompts" in spec:
            return await self._generate_batch(spec, writer, span, conn)
        stream = bool(spec.pop("stream", True))
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_token(token, done):
            # token None is the stream-END sentinel (cancelled /
            # migrated away without a final token) — forward it, the
            # consumer loops end without appending
            loop.call_soon_threadsafe(
                q.put_nowait,
                (None if token is None else int(token), bool(done)),
            )

        def do_submit():
            # off-loop: the engine lock may be held by a long step()
            # (or a first-call compile) — waiting for it must block
            # THIS request only, not the whole event loop
            with self._engine_lock:
                if self._stopping.is_set():
                    raise _HttpError(503, "gateway is stopping")
                return self.engine.submit(
                    spec["prompt"], spec["max_new_tokens"],
                    on_token=on_token, **self._submit_kwargs(spec),
                )

        try:
            req = await loop.run_in_executor(None, do_submit)
        except (ValueError, TypeError) as e:
            raise _HttpError(400, str(e))
        if span is not None:
            # the request's trace identity on the gateway span — rid
            # is minted by the engine, so it only exists post-submit
            span.set(rid=req.rid)
        if req.error is not None:
            # rejected at submit — backpressure on the wire. The rid
            # still echoes (ISSUE 12): the rejection has a flight
            # record too, and the client can fetch its trace.
            rid_hdr = ("X-Request-Id", str(req.rid))
            if isinstance(req.error, AdmissionRejected):
                raise _HttpError(
                    429, str(req.error),
                    extra_headers=(
                        ("Retry-After",
                         str(max(1, round(req.error.retry_after_s)))),
                        rid_hdr,
                    ),
                )
            raise _HttpError(422, str(req.error), extra_headers=(rid_hdr,))
        self._work.set()  # wake the driver
        if stream:
            conn.persist = False  # the SSE stream owns this socket
            return await self._stream_sse(req, q, writer)
        return await self._respond_once(req, q, writer, conn)

    async def _generate_batch(self, spec, writer, span, conn) -> int:
        """The ``prompts`` batch form (ISSUE 15 satellite — ROADMAP
        item 2): one POST carries N prompts, amortizing the handshake
        and request parse. Each prompt is a NORMAL ``submit()`` —
        admission control, policy accounting, and the paged never-fit
        rejection see them individually, so one shed prompt comes
        back as ITS entry's error while the rest serve.

        ``stream: false`` answers one JSON document with a
        ``results`` array (index-aligned with ``prompts``);
        ``stream: true`` multiplexes every request onto ONE SSE
        stream: an opening ``data: {"rids": [...]}`` event, then
        ``data: {"rid": r, "token": t, "done": d}`` per token in
        arrival order, then an ``event: done`` summary."""
        prompts = spec.pop("prompts")
        stream = bool(spec.pop("stream", True))
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        try:
            # batch-WIDE fields (shared by every prompt) fail the
            # whole request as a clean 400, exactly like the
            # single-prompt form's do_submit mapping — an uncaught
            # float("hot") here would sever the connection with no
            # response at all
            kwargs = self._submit_kwargs(spec)
        except (ValueError, TypeError) as e:
            raise _HttpError(400, str(e))
        max_new = spec["max_new_tokens"]

        def make_cb(i):
            def on_token(token, done):
                loop.call_soon_threadsafe(
                    q.put_nowait,
                    (i, None if token is None else int(token),
                     bool(done)),
                )

            return on_token

        def do_submit():
            out = []
            with self._engine_lock:
                if self._stopping.is_set():
                    raise _HttpError(503, "gateway is stopping")
                for i, p in enumerate(prompts):
                    try:
                        r = self.engine.submit(
                            p, max_new, on_token=make_cb(i), **kwargs
                        )
                    except (ValueError, TypeError) as e:
                        out.append((e, True))
                    else:
                        # classify HERE, under the engine lock: done
                        # at this instant can only mean a submit-time
                        # reject (shed / never-fit — it never feeds
                        # its queue). Snapshotting done AFTER the lock
                        # releases raced the driver thread: a 1-token
                        # request it finished in between looked like a
                        # reject and its queued tokens were never
                        # drained.
                        out.append((r, r.done))
            return out

        submitted = await loop.run_in_executor(None, do_submit)
        if span is not None:
            span.set(batch=len(prompts))
        entries = []
        pending: set[int] = set()
        for i, (r, rejected) in enumerate(submitted):
            if isinstance(r, BaseException):
                entries.append({
                    "index": i, "rid": None, "tokens": [],
                    "error": str(r),
                })
            else:
                entries.append({
                    "index": i, "rid": r.rid, "tokens": [],
                    "error": (
                        None if r.error is None else str(r.error)
                    ),
                })
                if not rejected:
                    pending.add(i)
        submitted = [r for r, _rejected in submitted]
        self._work.set()
        if stream:
            conn.persist = False
            return await self._stream_batch_sse(
                entries, pending, submitted, q, writer
            )
        while pending:
            i, token, done = await q.get()
            if token is not None:
                entries[i]["tokens"].append(token)
            if done:
                pending.discard(i)
                r = submitted[i]
                entries[i]["error"] = (
                    None if r.error is None else str(r.error)
                )
        for i, r in enumerate(submitted):
            if not isinstance(r, BaseException):
                entries[i]["full_sequence"] = (
                    list(r.prompt) + list(r.tokens)
                )
        await self._write(writer, _json_response(
            200, {"results": entries}, close=conn.close_header(),
        ))
        return 200

    async def _stream_batch_sse(self, entries, pending, submitted, q,
                                writer) -> int:
        head = (
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n"
        )
        self._m_sse_active.inc()
        try:
            await self._write(writer, head)
            await self._write(writer, _sse_event({
                "rids": [e["rid"] for e in entries],
                "errors": {
                    str(e["index"]): e["error"]
                    for e in entries if e["error"] is not None
                },
            }))
            while pending:
                i, token, done = await q.get()
                rid = entries[i]["rid"]
                if token is not None:
                    await self._write(writer, _sse_event(
                        {"rid": rid, "token": token, "done": done}
                    ))
                if done:
                    pending.discard(i)
            final = {
                "rids": [e["rid"] for e in entries],
                "n_tokens": {
                    str(e["rid"]): len(submitted[e["index"]].tokens)
                    for e in entries if e["rid"] is not None
                },
                "errors": {
                    str(e["rid"]):
                        None if submitted[e["index"]].error is None
                        else str(submitted[e["index"]].error)
                    for e in entries if e["rid"] is not None
                },
            }
            await self._write(writer, _sse_event(final, event="done"))
        except (ConnectionError, OSError) as e:
            # client went away mid-stream: cancel every still-live
            # request of the batch (the single-stream disconnect rule,
            # batch-wide)
            logger.info(
                "batch SSE client disconnected mid-stream (%r) — "
                "cancelling %d live requests", e, len(pending),
            )
            if not self._stopping.is_set() and pending:
                loop = asyncio.get_running_loop()
                rids = [
                    entries[i]["rid"] for i in pending
                    if entries[i]["rid"] is not None
                ]

                def do_cancel():
                    with self._engine_lock:
                        for rid in rids:
                            self.engine.cancel(rid)

                await loop.run_in_executor(None, do_cancel)
        finally:
            self._m_sse_active.dec()
        return 200

    async def _drain_tokens(self, req, q) -> list:
        tokens = []
        while True:
            token, done = await q.get()
            if token is not None:
                tokens.append(token)
            if done:
                return tokens

    async def _respond_once(self, req, q, writer, conn=None) -> int:
        tokens = await self._drain_tokens(req, q)
        payload = {
            "rid": req.rid,
            "tokens": tokens,
            "full_sequence": list(req.prompt) + list(req.tokens),
            "error": None if req.error is None else str(req.error),
        }
        await self._write(writer, _json_response(
            200, payload,
            extra_headers=(("X-Request-Id", str(req.rid)),),
            close=True if conn is None else conn.close_header(),
        ))
        return 200

    async def _stream_sse(self, req, q, writer) -> int:
        # trace-context echo on the wire (ISSUE 12): the engine-minted
        # rid rides a header (greppable by proxies) AND the opening
        # data event (greppable by SSE consumers)
        head = (
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"X-Request-Id: " + str(req.rid).encode("ascii") + b"\r\n"
            b"Connection: close\r\n\r\n"
        )
        self._m_sse_active.inc()
        try:
            await self._write(writer, head)
            await self._write(writer, _sse_event({"rid": req.rid}))
            while True:
                token, done = await q.get()
                if token is not None:
                    await self._write(
                        writer,
                        _sse_event({"token": token, "done": done}),
                    )
                if done:
                    break
            final = {
                "rid": req.rid,
                "n_tokens": len(req.tokens),
                "error": None if req.error is None else str(req.error),
            }
            await self._write(writer, _sse_event(final, event="done"))
        except (ConnectionError, OSError) as e:
            # client went away mid-stream: CANCEL the request so its
            # slot/blocks reclaim now (ISSUE 14 satellite — before
            # this, a disconnected client's request decoded to
            # completion into a queue nobody reads). Off-loop like
            # every engine call; skipped during stop(), whose sever
            # path also lands here — teardown must not queue cancels
            # behind a lock the driver is about to release for good.
            logger.info(
                "SSE client for request %d disconnected mid-stream "
                "(%r) — cancelling", req.rid, e,
            )
            if not self._stopping.is_set():
                loop = asyncio.get_running_loop()

                def do_cancel():
                    with self._engine_lock:
                        return self.engine.cancel(req.rid)

                await loop.run_in_executor(None, do_cancel)
        finally:
            self._m_sse_active.dec()
        return 200


def _wants_openmetrics(accept: str) -> bool:
    """Does this ``Accept`` header prefer the OpenMetrics exposition?
    Media types compare case-insensitively (RFC 9110) and q-values are
    honored, so ``application/openmetrics-text;q=0.1, text/plain``
    stays on 0.0.4 while ``Application/OpenMetrics-Text`` gets
    exemplars — a substring test got both wrong."""

    def _q(params) -> float:
        for p in params:
            k, _, v = p.partition("=")
            if k.strip() == "q":
                try:
                    return float(v.strip())
                except ValueError:
                    return 0.0
        return 1.0

    om_q, plain_q = 0.0, 0.0
    for media_range in accept.lower().split(","):
        mtype, *params = media_range.split(";")
        mtype = mtype.strip()
        q = _q(params)
        if mtype == "application/openmetrics-text":
            om_q = max(om_q, q)
        elif mtype in ("text/plain", "text/*", "*/*"):
            plain_q = max(plain_q, q)
    return om_q > 0.0 and om_q >= plain_q


def _sse_event(obj, event: str | None = None) -> bytes:
    prefix = f"event: {event}\n" if event else ""
    return (prefix + "data: " + json.dumps(obj) + "\n\n").encode("utf-8")
