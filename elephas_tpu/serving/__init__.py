"""Continuous-batching inference serving (ISSUE 1 tentpole).

Every decode entry point before this subsystem was a one-shot,
whole-batch call: all prompts start together, the batch stalls until
its slowest sequence finishes, and every new ``(batch, length)`` shape
risks a fresh XLA compile. This package converts that dead time into
throughput the way modern LLM servers do (Orca-style iteration-level
scheduling, vLLM-style slot/paged KV):

- :mod:`elephas_tpu.serving.kv_cache` — a fixed slot arena of
  per-layer K/V caches with per-slot write cursors, so sequences of
  different lengths coexist inside ONE compiled decode step;
- :mod:`elephas_tpu.serving.scheduler` — iteration-level admission of
  queued requests into free slots, immediate reclamation on
  EOS/max-tokens, and bucketed prompt padding that keeps the compiled
  shape set small and fixed;
- :mod:`elephas_tpu.serving.prefix_cache` — a deterministic radix
  index over cached prompt prefixes (ISSUE 4): finished requests'
  prompt K/V stays resident as donor slots with refcounts + LRU
  eviction, so shared system prompts prefill once fleet-wide;
- :mod:`elephas_tpu.serving.engine` — :class:`InferenceEngine`, the
  host-side driver (surfaced as ``SparkModel.serve()``): submit
  requests at any time, stream tokens back per request, run the same
  fixed-shape jitted step for the life of the server;
- :mod:`elephas_tpu.serving.paged_kv` + :mod:`elephas_tpu.serving.\
blocks` — the paged arena (ISSUE 7, ``serve(paged=True)``): a global
  block pool with per-slot block tables, so each request reserves only
  its OWN worst case, prompt-prefix blocks share copy-free by refcount
  (:class:`~elephas_tpu.serving.prefix_cache.PagedPrefixIndex`), and
  low-priority requests can be preempted — K/V swapped to host — and
  later resumed bit-exact;
- :mod:`elephas_tpu.serving.speculative` — draft-and-verify
  speculative decoding (ISSUE 8, ``serve(speculative=True)``): an
  n-gram prompt-lookup drafter or a small draft model proposes up to
  ``spec_k`` tokens per slot, ONE batched verify forward scores them
  over either arena, and the longest greedy-matching prefix (plus a
  bonus token) lands per round — several tokens per target forward,
  temperature-0 output bit-exact, with a per-request acceptance
  throttle so hostile text falls back to plain decode;
- :mod:`elephas_tpu.serving.policy` — pluggable SLO admission
  policies (ISSUE 10, ``serve(policy=, tenants=)``): VTC-style
  per-tenant token-weighted fair share, deadline-EDF ordering with an
  aging no-starvation bound, and overload admission control that
  rejects loudly instead of queueing into a guaranteed timeout —
  reordering and rejecting only, never touching decoding;
- :mod:`elephas_tpu.serving.gateway` — the async HTTP/1.1 front door
  (ISSUE 10, ``serve(gateway_port=)``): ``POST /v1/generate`` with
  SSE token streaming over the per-request ``on_token`` hook,
  ``GET /metrics`` / ``GET /stats``, 429 + Retry-After backpressure
  from the policy's admission verdict, and sever-on-stop connection
  hygiene. ISSUE 12 adds the per-request observability surface:
  ``GET /healthz`` (fleet-router liveness), ``GET /v1/requests/{rid}/
  trace`` (the engine's flight-recorder lifecycle record), ``GET
  /debug/engine`` (live slot/queue/pool snapshot), an ``X-Request-Id``
  echo on every generate response, and OpenMetrics exemplars linking
  TTFT/ITL histogram buckets to the rid that landed in them.
"""

from elephas_tpu.serving.blocks import BlockAllocator  # noqa: F401
from elephas_tpu.serving.engine import (  # noqa: F401
    InferenceEngine,
    RequestCancelled,
)
from elephas_tpu.serving.pp_engine import PPEngine  # noqa: F401
from elephas_tpu.serving.prefix_cache import (  # noqa: F401
    PagedPrefixIndex,
    PrefixCache,
)
from elephas_tpu.serving.scheduler import (  # noqa: F401
    Admission,
    Preemption,
    Request,
    Scheduler,
    bucket_for,
    default_buckets,
)
from elephas_tpu.serving.kv_cache import SlotKVCache  # noqa: F401
from elephas_tpu.serving.paged_kv import (  # noqa: F401
    PagedKVPool,
    blocks_for,
    table_buckets,
)
from elephas_tpu.serving.speculative import (  # noqa: F401
    AcceptanceThrottle,
    DraftModelDrafter,
    Drafter,
    NgramDrafter,
)
from elephas_tpu.serving.gateway import Gateway  # noqa: F401
from elephas_tpu.serving.policy import (  # noqa: F401
    DEFAULT_TENANT,
    AdmissionRejected,
    FairSharePolicy,
    FifoPolicy,
    Policy,
    resolve_policy,
)
