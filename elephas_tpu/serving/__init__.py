"""Continuous-batching inference serving (ISSUE 1 tentpole).

Every decode entry point before this subsystem was a one-shot,
whole-batch call: all prompts start together, the batch stalls until
its slowest sequence finishes, and every new ``(batch, length)`` shape
risks a fresh XLA compile. This package converts that dead time into
throughput the way modern LLM servers do (Orca-style iteration-level
scheduling, vLLM-style slot/paged KV):

- :mod:`elephas_tpu.serving.kv_cache` — a fixed slot arena of
  per-layer K/V caches with per-slot write cursors, so sequences of
  different lengths coexist inside ONE compiled decode step;
- :mod:`elephas_tpu.serving.scheduler` — iteration-level admission of
  queued requests into free slots, immediate reclamation on
  EOS/max-tokens, and bucketed prompt padding that keeps the compiled
  shape set small and fixed;
- :mod:`elephas_tpu.serving.prefix_cache` — a deterministic radix
  index over cached prompt prefixes (ISSUE 4): finished requests'
  prompt K/V stays resident as donor slots with refcounts + LRU
  eviction, so shared system prompts prefill once fleet-wide;
- :mod:`elephas_tpu.serving.engine` — :class:`InferenceEngine`, the
  host-side driver (surfaced as ``SparkModel.serve()``): submit
  requests at any time, stream tokens back per request, run the same
  fixed-shape jitted step for the life of the server.
"""

from elephas_tpu.serving.engine import InferenceEngine  # noqa: F401
from elephas_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from elephas_tpu.serving.scheduler import (  # noqa: F401
    Admission,
    Request,
    Scheduler,
    bucket_for,
    default_buckets,
)
from elephas_tpu.serving.kv_cache import SlotKVCache  # noqa: F401
