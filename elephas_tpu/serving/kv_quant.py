"""Quantized paged-KV block codec (ISSUE 19).

The paged arena (:mod:`~elephas_tpu.serving.paged_kv`) prices
admission in BYTES: every resident position costs ``2 · H · Dh``
float32 values per layer, and on a fixed per-device KV budget that
byte price is exactly what caps concurrency. This module is the
KIVI/KVQuant-style answer: store pool blocks as **int8 or packed int4
with per-(position, head) float32 scales**, quantize on write inside
the serving programs, and dequantize inside the flash span tiles —
fp rows never materialize outside one ``[B, block_k, H, Dh]`` tile.

Scale granularity is per (pool row position, head) — NOT one scale
per block — deliberately: each token's write touches only its own
``(block, offset)`` row, so quantize-on-write needs no read-modify-
write of a shared block statistic, both the one-hot contraction and
``local=True`` native-scatter write paths stay exact and incremental,
and an offloaded/migrated block is a self-contained byte string
(values + scales move together, bit-identically).

Symmetric quantization, zero-point-free::

    scale = max(|x|) / qmax        (qmax: 127 for int8, 7 for int4)
    q     = round(x / scale)  in [-qmax, qmax]
    x'    = q * scale

An all-zero row quantizes to ``scale == 0`` and dequantizes to exact
zeros (``q * 0``) — sentinel-padded pool rows stay exact zeros through
the round-trip, which the paged gather math relies on.

int4 packs two signed nibbles per int8 byte along the head_dim axis
(lo nibble = even index, hi nibble = odd index; odd ``Dh`` zero-pads
the last nibble). Unpacking is two arithmetic shifts — sign extension
for free, no lookup tables.

Every helper has a numpy twin (``*_np``) for the host side: stage-
parallel prefill handoffs land host fp rows into a quantized pool, and
the wire/refusal tests exercise the codec without a device.

Temp-0 exactness CANNOT survive quantization — the parity contract
changes shape (see docs/API.md "Quantized KV"): ``kv_dtype="fp"`` is
the selectable parity oracle (exactly like ``attention="naive"``),
bit-exactness is asserted WITHIN a kv_dtype (quantized blocks offload,
migrate, and resume bit-identically), and cross-dtype quality is gated
by token agreement / logprob deltas against the fp oracle.
"""

from __future__ import annotations

__all__ = [
    "KV_DTYPES",
    "QMAX",
    "packed_head_dim",
    "pool_bytes_per_pos",
    "quantize_rows",
    "dequantize_rows",
    "pack_int4",
    "unpack_int4",
    "quantize_rows_np",
    "dequantize_rows_np",
]

KV_DTYPES = ("fp", "int8", "int4")

QMAX = {"int8": 127.0, "int4": 7.0}


def check_kv_dtype(kv_dtype: str) -> str:
    """Validate a ``kv_dtype`` knob value loudly (engine/serve() and
    the wire importer both refuse unknown dtypes up front)."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}"
        )
    return kv_dtype


def packed_head_dim(head_dim: int, kv_dtype: str) -> int:
    """STORED last-axis width for one ``head_dim``-wide row: ``Dh``
    int8 bytes for int8, ``ceil(Dh / 2)`` packed bytes for int4."""
    if kv_dtype == "int4":
        return -(-int(head_dim) // 2)
    return int(head_dim)


def pool_bytes_per_pos(specs, kv_dtype: str) -> int:
    """Bytes one resident position costs across all layers (K and V):
    the honest per-device KV price the bench's equal-bytes concurrency
    gate divides by. ``specs`` is ``[(name, heads, head_dim), ...]``."""
    if kv_dtype == "fp":
        return sum(h * d for _, h, d in specs) * 2 * 4
    # quantized: 1 byte per stored value + one f32 scale per head
    return sum(
        h * packed_head_dim(d, kv_dtype) + h * 4 for _, h, d in specs
    ) * 2


def pack_int4(q):
    """Pack signed int4 values (int8 storage, range [-7, 7]) two per
    byte along the LAST axis: even index → lo nibble, odd index → hi
    nibble; odd-length axes zero-pad the final hi nibble. ``[..., D]``
    int8 → ``[..., ceil(D/2)]`` int8."""
    import jax.numpy as jnp

    d = q.shape[-1]
    if d % 2:
        pad = [(0, 0)] * (q.ndim - 1) + [(0, 1)]
        q = jnp.pad(q, pad)
    lo = q[..., 0::2]
    hi = q[..., 1::2]
    return ((lo & 0x0F) | (hi << 4)).astype(jnp.int8)


def unpack_int4(p, head_dim: int):
    """Inverse of :func:`pack_int4`: ``[..., ceil(D/2)]`` int8 →
    ``[..., head_dim]`` int8 via sign-extending arithmetic shifts
    (``(p << 4) >> 4`` recovers the lo nibble, ``p >> 4`` the hi)."""
    import jax.numpy as jnp

    p = p.astype(jnp.int8)
    lo = (p << 4) >> 4
    hi = p >> 4
    out = jnp.stack([lo, hi], axis=-1).reshape(
        p.shape[:-1] + (2 * p.shape[-1],)
    )
    return out[..., : int(head_dim)]


def quantize_rows(x, kv_dtype: str):
    """Quantize fp rows ``[..., H, Dh]`` → ``(q, scale)``: ``q`` int8
    ``[..., H, Dhp]`` (int4 packed when asked), ``scale`` float32
    ``[..., H]``. Symmetric per-(row, head); all-zero rows get
    ``scale == 0`` and round-trip to exact zeros."""
    import jax.numpy as jnp

    qmax = QMAX[kv_dtype]
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)  # [..., H]
    scale = amax / qmax
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(
        jnp.round(x / safe[..., None]), -qmax, qmax
    ).astype(jnp.int8)
    if kv_dtype == "int4":
        q = pack_int4(q)
    return q, scale


def dequantize_rows(q, scale, kv_dtype: str, head_dim: int):
    """Inverse of :func:`quantize_rows`: ``(q [..., H, Dhp] int8,
    scale [..., H] f32)`` → float32 ``[..., H, head_dim]``. This is
    the in-tile seam — flash callers hand it ONE K/V tile at a time,
    so fp never materializes beyond a tile."""
    import jax.numpy as jnp

    if kv_dtype == "int4":
        q = unpack_int4(q, head_dim)
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def quantize_rows_np(x, kv_dtype: str):
    """Host (numpy) twin of :func:`quantize_rows` — bit-identical
    quantization decisions (same symmetric scale, same round-half-to-
    even), used when stage-parallel prefill lands host fp rows into a
    quantized pool and by the codec tests."""
    import numpy as np

    qmax = QMAX[kv_dtype]
    x = np.asarray(x, dtype=np.float32)
    amax = np.max(np.abs(x), axis=-1)
    scale = (amax / qmax).astype(np.float32)
    safe = np.where(scale > 0.0, scale, 1.0)
    q = np.clip(
        np.round(x / safe[..., None]), -qmax, qmax
    ).astype(np.int8)
    if kv_dtype == "int4":
        d = q.shape[-1]
        if d % 2:
            pad = [(0, 0)] * (q.ndim - 1) + [(0, 1)]
            q = np.pad(q, pad)
        q = ((q[..., 0::2] & 0x0F) | (q[..., 1::2] << 4)).astype(
            np.int8
        )
    return q, scale


def dequantize_rows_np(q, scale, kv_dtype: str, head_dim: int):
    """Host (numpy) twin of :func:`dequantize_rows`."""
    import numpy as np

    q = np.asarray(q, dtype=np.int8)
    if kv_dtype == "int4":
        lo = (q << 4) >> 4
        hi = q >> 4
        q = np.stack([lo, hi], axis=-1).reshape(
            q.shape[:-1] + (2 * q.shape[-1],)
        )[..., : int(head_dim)]
    return q.astype(np.float32) * np.asarray(
        scale, dtype=np.float32
    )[..., None]
