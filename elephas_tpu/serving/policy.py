"""SLO-aware admission policies (ISSUE 10 tentpole).

The scheduler's admission order used to be hard-wired FIFO: under a
mixed multi-tenant load, one heavy tenant (or one burst of long
prompts) parks everyone else's time-to-first-token behind its own
prefills, and nothing ever says "no" — the queue just grows until
every request misses its deadline together. This module makes the
order (and the right to enter the queue at all) a pluggable
:class:`Policy`:

- :class:`FifoPolicy` — the legacy order, now explicit: submission
  order, admit everything. The zero-policy engine still bypasses the
  hook entirely, so existing callers pay nothing.
- :class:`FairSharePolicy` — token-weighted fair queueing across
  tenants in the style of VTC ("Fairness in Serving Large Language
  Models", Sheng et al., OSDI 2024): each tenant carries a **virtual
  token counter** advanced by the tokens actually served for it
  (prefill and decode tokens at separate weights, normalized by the
  tenant's share weight), and every admission wave serves the
  backlogged tenant with the smallest counter. Within a tenant's turn
  requests order **earliest-deadline-first** (tighter
  ``ttft_deadline_ms`` first, submission order inside a deadline
  class), and an **aging** bound promotes any request that has waited
  ``aging_waves`` admission waves to the queue front — no request
  starves, whatever the counters say. ``max_queue_tokens`` adds
  overload **admission control**: the queue token budget divides
  across tenants by weight share, and a submit that would push its
  OWN tenant's outstanding token debt past that share is rejected
  loudly (:class:`AdmissionRejected`, carrying a deterministic
  Retry-After hint) instead of joining a queue it could only ever
  time out in — load shedding falls on the tenant causing the
  overload, never on its neighbors.

Everything here is host-side bookkeeping on the gang-replicated
schedule, so the same determinism rules as the scheduler apply: no
wall clock anywhere near an ordering decision. Deadlines order by
their *declared* millisecond budgets (a deadline CLASS), ages count
admission waves (a logical clock), and the virtual counters advance by
token counts — every gang process computes the identical order from
the identical submission sequence. Wall-clock TTFT only ever meets the
deadline in telemetry (the SLO attainment counters), never in the
schedule.

Fairness bound (the VTC property, adapted): for two tenants f and g
both backlogged over a window, the difference in weighted service
``|W_f / w_f - W_g / w_g|`` is bounded by a constant independent of
the window length — at most one maximal request's token cost per
tenant (the head request the wave committed to before the counters
crossed). FIFO has no such bound: the gap grows linearly with the
heavy tenant's backlog.
"""

from __future__ import annotations

import math
from collections import deque

#: Label every tenant-less request accounts under. Declaring a tenant
#: literally named "default" simply merges with it.
DEFAULT_TENANT = "default"


class AdmissionRejected(RuntimeError):
    """A submit refused by the policy's overload admission control.

    ``retry_after_s`` is the policy's deterministic backoff hint — the
    gateway surfaces it as a ``Retry-After`` header on the 429."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class Verdict:
    """One admission-control decision for one submit."""

    __slots__ = ("admitted", "retry_after_s", "reason")

    def __init__(self, admitted: bool, retry_after_s: float = 0.0,
                 reason: str = ""):
        self.admitted = bool(admitted)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


ADMIT = Verdict(True)


def normalize_tenants(tenants) -> dict:
    """``{name: weight}`` from a dict, an iterable of names (weight
    1.0 each), or None (no declared tenants). Loud on bad weights."""
    if tenants is None:
        return {}
    if isinstance(tenants, dict):
        out = {str(k): float(v) for k, v in tenants.items()}
    else:
        out = {str(t): 1.0 for t in tenants}
    for name, w in out.items():
        if not w > 0:
            raise ValueError(
                f"tenant {name!r} has non-positive weight {w} — a "
                f"zero/negative share can never be scheduled fairly"
            )
    return out


class Policy:
    """Admission-policy interface the scheduler and engine drive.

    Hooks, in request-lifecycle order:

    - :meth:`admission_verdict` — at ``submit()``, before the request
      joins the queue; a non-admitted verdict rejects it loudly.
    - :meth:`on_submit` — the request joined the waiting queue.
    - :meth:`begin_wave` / :meth:`reorder` — each admission wave ticks
      the logical age clock once, then the scheduler asks for the
      queue order before every single admission attempt (so the order
      can react to the charges of admissions earlier in the same wave).
    - :meth:`on_admit` — the request leased its slot (``resumed`` when
      it is a preemption resume, which must not re-charge prefill).
    - :meth:`on_token` — one generated token emitted.
    - :meth:`on_finish` — the request left the engine (done or failed).
    - :meth:`priority_of` — the preemption-effective priority; paged
      preemption compares THESE, so a policy can let deadline traffic
      outrank best-effort without callers touching ``submit(priority=)``.

    Subclasses override what they need; the base is a valid no-op
    policy that admits everything in submission order."""

    #: submit(ttft_deadline_ms=) is refused unless the engine's policy
    #: actually reads deadlines — a deadline nobody schedules by is a
    #: silent lie to the caller.
    reads_deadlines = False

    def __init__(self, tenants=None):
        self.tenants = normalize_tenants(tenants)

    # -- identity ------------------------------------------------------

    @property
    def tenant_names(self) -> tuple:
        """Every label value the engine should pre-register."""
        names = set(self.tenants) | {DEFAULT_TENANT}
        return tuple(sorted(names))

    def knows(self, tenant) -> bool:
        """Is ``tenant`` a legal label for this policy? ``None`` (the
        implicit default tenant) always is; a named tenant must be
        declared up front when any are."""
        if tenant is None or tenant == DEFAULT_TENANT:
            return True
        return tenant in self.tenants

    def resolve(self, tenant) -> str:
        return DEFAULT_TENANT if tenant is None else str(tenant)

    # -- lifecycle hooks (no-op defaults) ------------------------------

    def admission_verdict(self, req, queued_tokens: int,
                          tenant_queued_tokens: int = 0) -> Verdict:
        return ADMIT

    def on_submit(self, req) -> None:
        pass

    def begin_wave(self) -> None:
        pass

    def reorder(self, waiting: deque, pinned=()) -> None:
        pass

    def on_admit(self, req, resumed: bool = False) -> None:
        pass

    def on_preempt(self, req) -> None:
        pass

    def on_token(self, req) -> None:
        pass

    def on_finish(self, req) -> None:
        pass

    def priority_of(self, req) -> int:
        return req.priority

    def stats(self) -> dict:
        """Policy-internal state for ``engine.stats()['policy']``."""
        return {"name": type(self).__name__}

    def snapshot_counters(self) -> dict:
        """Per-tenant virtual-counter snapshot for the flight record's
        admission-verdict entry (ISSUE 12): the fairness state the
        verdict was decided against, so ``explain(rid)`` can answer
        "queued behind whose debt?". Cheap and read-only — policies
        without counters return ``{}``."""
        return {}


class FifoPolicy(Policy):
    """Submission order, admit everything — the legacy behavior as an
    explicit policy object (useful as the control arm of an A/B, and
    for tenant-labeled accounting without fairness)."""


class FairSharePolicy(Policy):
    """VTC-style token-weighted fair share + deadline EDF + aging +
    overload admission control. See the module docstring for the
    scheduling story; knobs:

    - ``tenants``: ``{name: weight}`` (or iterable, weight 1.0). The
      implicit ``"default"`` tenant always exists at weight 1.0 unless
      declared otherwise.
    - ``prefill_weight`` / ``decode_weight``: virtual-counter cost per
      prompt/generated token (VTC uses 1/2 — decode tokens cost more
      service per token than prefill's batched FLOPs).
    - ``max_queue_tokens``: overload bound on the waiting queue's
      outstanding token debt (prompt + remaining budget, summed) —
      divided across tenants by WEIGHT SHARE, so each tenant sheds
      against its own slice of the queue budget and a hog's backlog
      can never crowd a light tenant out of admission (shedding falls
      on the tenant causing the debt). ``None`` disables admission
      control.
    - ``aging_waves``: admission waves a request may wait before it is
      promoted to the queue front regardless of its tenant's counter.
      Waves tick once per engine step (every ``begin_wave``), so this
      is a bound in SCHEDULING OPPORTUNITIES, not requests — size it
      in step counts. Too small and an unadmittable promoted request
      (e.g. a preempted heavy resume waiting for blocks) head-blocks
      urgent arrivals, re-creating in miniature the FIFO collapse the
      policy exists to prevent; the default is deliberately lazy —
      aging is the starvation BACKSTOP, not the scheduler.
    - ``deadline_boost``: preemption-priority bump for requests that
      carry a TTFT deadline and have not emitted their first token yet
      (composes with paged ``preemption=True``: deadline traffic may
      swap out best-effort work; once the first token lands, the TTFT
      is settled and the bump drops).
    - ``retry_after_s``: base Retry-After hint; the actual hint scales
      deterministically with how far past the bound the queue is.
    """

    reads_deadlines = True

    def __init__(self, tenants=None, *, prefill_weight: float = 1.0,
                 decode_weight: float = 2.0,
                 max_queue_tokens: int | None = None,
                 aging_waves: int = 256, deadline_boost: int = 1,
                 retry_after_s: float = 1.0):
        super().__init__(tenants)
        if prefill_weight < 0 or decode_weight < 0:
            raise ValueError(
                f"token weights must be non-negative, got prefill="
                f"{prefill_weight} decode={decode_weight}"
            )
        if max_queue_tokens is not None and int(max_queue_tokens) < 1:
            raise ValueError(
                f"max_queue_tokens={max_queue_tokens} < 1 would reject "
                f"every request — use None to disable admission control"
            )
        if aging_waves < 1:
            raise ValueError(f"aging_waves={aging_waves} < 1")
        if retry_after_s <= 0:
            raise ValueError(f"retry_after_s={retry_after_s} <= 0")
        self.prefill_weight = float(prefill_weight)
        self.decode_weight = float(decode_weight)
        self.max_queue_tokens = (
            None if max_queue_tokens is None else int(max_queue_tokens)
        )
        self.aging_waves = int(aging_waves)
        self.deadline_boost = int(deadline_boost)
        self.retry_after_s = float(retry_after_s)
        # virtual token counters: weighted service each tenant has
        # received; the wave serves the smallest. Monotone within a
        # tenant; lifted on arrival-after-idle so an idle tenant cannot
        # bank unbounded credit (the VTC lift).
        self._vtc: dict[str, float] = {}
        # outstanding (queued + active) requests per tenant — drives
        # the lift and the "backlogged" notion in the fairness bound
        self._outstanding: dict[str, int] = {}
        # logical age clock: wave index at first sight of each rid
        self._wave = 0
        self._seen: dict[int, int] = {}
        # report-only tallies for stats()
        self._rejected = 0

    def _weight(self, tenant: str) -> float:
        return self.tenants.get(tenant, 1.0)

    # -- admission control ---------------------------------------------

    def _share(self, tenant: str) -> float:
        """``tenant``'s slice of the queue token budget: its weight
        over the declared total (an undeclared/default tenant rides at
        weight 1.0 against the same denominator)."""
        total = sum(self.tenants.values()) or 1.0
        return self.max_queue_tokens * self._weight(tenant) / total

    def admission_verdict(self, req, queued_tokens: int,
                          tenant_queued_tokens: int = 0) -> Verdict:
        if self.max_queue_tokens is None:
            return ADMIT
        t = self.resolve(req.tenant)
        share = self._share(t)
        debt = (
            int(tenant_queued_tokens)
            + len(req.prompt) + req.max_new_tokens
        )
        if debt <= share:
            return ADMIT
        self._rejected += 1
        # deterministic backoff hint: scale the base by how many full
        # shares deep the tenant's debt would be — a queue 3 shares
        # deep needs roughly 3 drain windows, not 1
        hint = self.retry_after_s * math.ceil(debt / share)
        return Verdict(
            False, retry_after_s=hint,
            reason=(
                f"tenant {t!r} queue token debt {debt} would exceed "
                f"its admission bound {share:g} (weight share of "
                f"{self.max_queue_tokens})"
            ),
        )

    # -- lifecycle ------------------------------------------------------

    def on_submit(self, req) -> None:
        t = self.resolve(req.tenant)
        n = self._outstanding.get(t, 0)
        if n == 0:
            # VTC lift: a tenant returning from idle starts at the
            # floor of the currently-backlogged tenants' counters —
            # idle time earns no credit against active tenants
            busy = [
                self._vtc.get(u, 0.0)
                for u, c in self._outstanding.items() if c > 0
            ]
            if busy:
                self._vtc[t] = max(self._vtc.get(t, 0.0), min(busy))
        self._outstanding[t] = n + 1
        self._seen.setdefault(req.rid, self._wave)

    def begin_wave(self) -> None:
        self._wave += 1

    def _charge(self, tenant: str, cost: float) -> None:
        self._vtc[tenant] = (
            self._vtc.get(tenant, 0.0) + cost / self._weight(tenant)
        )

    def on_admit(self, req, resumed: bool = False) -> None:
        self._seen.pop(req.rid, None)
        if not resumed:
            self._charge(
                self.resolve(req.tenant),
                self.prefill_weight * len(req.prompt),
            )

    def on_preempt(self, req) -> None:
        # back in the queue: re-arm the aging clock so a preempted
        # request is bounded-wait like any other waiter (its tenant's
        # counter usually sorts it behind the traffic that preempted
        # it — aging is what guarantees it still resumes)
        self._seen.setdefault(req.rid, self._wave)

    def on_token(self, req) -> None:
        self._charge(self.resolve(req.tenant), self.decode_weight)

    def on_finish(self, req) -> None:
        t = self.resolve(req.tenant)
        n = self._outstanding.get(t, 0)
        if n > 0:
            self._outstanding[t] = n - 1
        self._seen.pop(req.rid, None)

    # -- ordering -------------------------------------------------------

    def _key(self, req):
        """Deterministic sort key: aged requests first (oldest
        arrival first — the starvation bound), then smallest tenant
        counter (fair share), then tightest declared deadline
        (deadline-class EDF), then submission order."""
        age = self._wave - self._seen.get(req.rid, self._wave)
        aged = age >= self.aging_waves
        dl = (
            req.ttft_deadline_ms
            if req.ttft_deadline_ms is not None else math.inf
        )
        return (
            0 if aged else 1,
            req.rid if aged else 0,
            self._vtc.get(self.resolve(req.tenant), 0.0),
            dl,
            req.rid,
        )

    def reorder(self, waiting: deque, pinned=()) -> None:
        """Rank EVERYONE by the fair-share key — including preempted
        requests awaiting resume (``pinned`` is deliberately ignored
        here). Resume-from-any-position is safe (the offloaded K/V
        waits on the host keyed by rid), and pinning a preempted
        heavy request at the front would head-block every later
        urgent arrival behind a resume that cannot fit yet — the
        exact FIFO collapse this policy exists to prevent. Aging is
        what bounds the preempted request's wait instead."""
        if len(waiting) < 2:
            return
        items = sorted(waiting, key=self._key)
        waiting.clear()
        waiting.extend(items)

    def priority_of(self, req) -> int:
        boost = (
            self.deadline_boost
            if req.ttft_deadline_ms is not None and not req.tokens
            else 0
        )
        return req.priority + boost

    # -- introspection --------------------------------------------------

    def snapshot_counters(self) -> dict:
        return {t: round(v, 3) for t, v in sorted(self._vtc.items())}

    def stats(self) -> dict:
        return {
            "name": type(self).__name__,
            "virtual_counters": {
                t: round(v, 3) for t, v in sorted(self._vtc.items())
            },
            "outstanding": dict(sorted(self._outstanding.items())),
            "wave": self._wave,
            "max_queue_tokens": self.max_queue_tokens,
            "rejected": self._rejected,
        }


def resolve_policy(policy, tenants=None):
    """The ``serve(policy=, tenants=)`` knob resolver: ``None`` (no
    policy at all — the legacy zero-overhead path) unless tenants are
    declared, a policy name (``"fifo"`` / ``"fair"``), or a
    :class:`Policy` instance. Loud on every ambiguous combination."""
    if policy is None:
        if tenants is None:
            return None
        # tenants declared without a policy: fair share is the only
        # reason to declare them — defaulting to FIFO would record
        # labels while silently not isolating anybody
        return FairSharePolicy(tenants)
    if isinstance(policy, str):
        name = policy.lower()
        if name == "fifo":
            return FifoPolicy(tenants)
        if name == "fair":
            return FairSharePolicy(tenants)
        raise ValueError(
            f"unknown policy {policy!r} — use 'fifo', 'fair', or a "
            f"serving.policy.Policy instance"
        )
    if not isinstance(policy, Policy):
        raise TypeError(
            f"policy must be a str or serving.policy.Policy, got "
            f"{type(policy).__name__}"
        )
    if tenants is not None:
        raise ValueError(
            "pass tenants= only with a policy name — a Policy instance "
            "already declared its own tenants, and merging two tenant "
            "sets silently would hide which weights actually apply"
        )
    return policy
