"""InferenceEngine — the continuous-batching serving loop.

One engine wraps one causal LM (a ``transformer_lm``-style FlashMHA
model) and serves any number of generation requests through two
program FAMILIES, compiled once each and reused for the life of the
server:

- one **prefill** program per prompt-length bucket (a closed, fixed
  ladder — :func:`~elephas_tpu.serving.scheduler.default_buckets`),
  writing a whole prompt's K/V into a leased slot in a single
  full-sequence forward;
- ONE **decode step** over the whole slot arena, advancing every
  in-flight sequence by one token at its own position (the vector
  write-cursor in :mod:`~elephas_tpu.serving.kv_cache`).

Each :meth:`InferenceEngine.step`: admit waiting requests into free
slots (prefill each), run the decode step, read the sampled tokens,
reclaim slots that hit EOS / their token budget. Requests can be
submitted at ANY time — they join the next step's admission wave
(iteration-level scheduling) — and finished slots free mid-flight, so
short sequences never hold long ones hostage the way one-shot batch
``generate()`` does.

Mesh-aware like the one-shot path: under a DP mesh the slot axis
shards over the batch axes; under TP the weights stay sharded through
``stateless_call`` with the planner's layouts and the arena shards
heads over the model axis. Every gang process must drive the engine
with the identical submission sequence (the SPMD contract ``generate``
already imposes); all read identical tokens.

Weights ride as jit ARGUMENTS, uploaded once at construction —
:meth:`refresh_weights` re-uploads after further training.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from elephas_tpu.serving.kv_cache import (
    SlotKVCache,
    prefill_forward,
    token_decode_step,
)
from elephas_tpu.serving.scheduler import (
    Request,
    Scheduler,
    default_buckets,
)

logger = logging.getLogger(__name__)


def _sample_dynamic(logits, key, temps, top_k, top_p):
    """Per-row sampling with a DYNAMIC temperature vector: rows with
    ``temps <= 0`` take greedy argmax (bit-identical to the one-shot
    path's temperature-0 branch), the rest temperature-scaled
    categorical under the engine's static top_k/top_p filters (same
    filter math as ``_sample_logits``)."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.models.transformer import _filter_logits

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _filter_logits(
        logits / jnp.maximum(temps, 1e-6)[:, None], top_k, top_p
    )
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


class InferenceEngine:
    """Continuous-batching server over a slot-based KV cache.

    ``num_slots`` bounds concurrent in-flight sequences (rounded up to
    the mesh's batch-axis product so the arena shards evenly);
    ``buckets`` overrides the prompt-padding ladder; ``top_k`` /
    ``top_p`` are engine-static sampling filters; per-request
    ``temperature`` rides as data (0 = greedy).

    PP ring decode is not integrated yet — construct via
    ``SparkModel.serve()`` on a DP/TP mesh, or directly on no mesh.
    """

    def __init__(self, model, num_slots: int = 8, mesh=None,
                 batch_axes=("data",), model_axis=None, rules=None,
                 top_k: int | None = None, top_p: float | None = None,
                 seed: int = 0, buckets=None, steps_per_sync: int = 1):
        import jax
        import jax.numpy as jnp

        from elephas_tpu.models.transformer import (
            validate_token_decode_model,
        )

        flash_layers, _stock, _gqa = validate_token_decode_model(
            model,
            what="the serving engine",
            hint="use one-shot generate()",
            allow_stock=False,
        )
        self.model = model
        self.maxlen = int(model.inputs[0].shape[1])
        self.vocab = int(model.outputs[0].shape[-1])
        self.top_k = top_k
        self.top_p = top_p
        if top_k is not None and not 0 < int(top_k) <= self.vocab:
            raise ValueError(
                f"top_k={top_k} outside (0, vocab={self.vocab}]"
            )
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p={top_p} outside (0, 1]")

        self.mesh = mesh
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        self.batch_axes = tuple(batch_axes)
        self.model_axis = model_axis
        if mesh is not None:
            missing = [a for a in self.batch_axes if a not in mesh.shape]
            if missing:
                raise ValueError(
                    f"batch_axes {missing} not in mesh axes "
                    f"{tuple(mesh.shape)}"
                )
            dp = int(
                np.prod([mesh.shape[a] for a in self.batch_axes])
            )
            if num_slots % dp:
                rounded = num_slots + (-num_slots) % dp
                logger.info(
                    "rounding num_slots %d -> %d (multiple of the "
                    "batch-axis product %d)", num_slots, rounded, dp,
                )
                num_slots = rounded
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots} < 1")
        self.num_slots = int(num_slots)

        if buckets is not None:
            buckets = tuple(int(b) for b in buckets)
            bad = [b for b in buckets if not 0 < b <= self.maxlen]
            if bad:
                raise ValueError(
                    f"buckets {bad} outside (0, maxlen={self.maxlen}] — "
                    f"a bucket beyond maxlen would overflow the KV arena"
                )

        self.arena = SlotKVCache(
            flash_layers, self.num_slots, self.maxlen,
            mesh=mesh, batch_axes=self.batch_axes, model_axis=model_axis,
        )
        self.scheduler = Scheduler(
            self.num_slots, buckets or default_buckets(self.maxlen)
        )
        self._rules = rules
        self._seed = int(seed)
        self.total_generated = 0
        # completed requests, BOUNDED: a server alive for millions of
        # requests must not grow host memory linearly — callers keep
        # their own Request handles from submit(); this registry only
        # feeds stats()/tests and evicts oldest past the bound
        self.finished: dict[int, Request] = {}
        self._finished_bound = 4096
        self.finished_count = 0

        maxlen, arena = self.maxlen, self.arena

        def _constrain_all(caches):
            heads = {name: h for name, h, _d in arena.specs}
            return {
                name: (
                    arena.constrain(k, heads[name]),
                    arena.constrain(v, heads[name]),
                )
                for name, (k, v) in caches.items()
            }

        def _vec(z):
            if mesh is None:
                return z
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(
                z, NamedSharding(mesh, P(self.batch_axes))
            )

        def init_state():
            caches = arena.init()
            lengths = _vec(jnp.zeros((self.num_slots,), jnp.int32))
            last = _vec(jnp.zeros((self.num_slots,), jnp.int32))
            temps = _vec(jnp.zeros((self.num_slots,), jnp.float32))
            return caches, lengths, last, temps

        def prefill(w, caches, lengths, last, temps, tokens_rows,
                    p_lens, admit, new_temps, key):
            logits, caches = prefill_forward(
                model, w, tokens_rows, caches, admit, maxlen
            )
            caches = _constrain_all(caches)
            # each row's next-token logits sit at its own prompt end —
            # one-hot contraction over the bucket axis (exact select,
            # and slot-local under the mesh unlike a per-row gather)
            S = tokens_rows.shape[1]
            at_end = (
                (p_lens - 1)[:, None] == jnp.arange(S)[None, :]
            ).astype(logits.dtype)
            last_logits = jnp.einsum("bs,bsv->bv", at_end, logits)
            key, sub = jax.random.split(key)
            firsts = _sample_dynamic(
                last_logits, sub, new_temps, self.top_k, self.top_p
            )
            lengths = _vec(jnp.where(admit, p_lens, lengths))
            last = _vec(jnp.where(admit, firsts, last))
            temps = _vec(jnp.where(admit, new_temps, temps))
            return caches, lengths, last, temps, key, firsts

        # multi-step scheduling (the vLLM/TensorRT-LLM trick): decode
        # `steps_per_sync` tokens per dispatch inside ONE fori_loop, so
        # program-launch + host-sync cost amortizes over the window.
        # Scheduling decisions (admission, reclaim) then happen at
        # window boundaries — k=1 is pure Orca iteration-level
        # scheduling; larger k trades up to k-1 wasted positions on a
        # mid-window finish for far fewer host round-trips. Greedy
        # (temperature-0) tokens are identical across k; sampled
        # streams match only while windows are fully consumed — a
        # drain that abandons a window tail still advanced the key k
        # times, so later temp>0 requests may sample differently than
        # under k=1 (deterministic per (seed, k, schedule) either way).
        k_window = max(1, int(steps_per_sync))
        self.steps_per_sync = k_window

        def decode(w, caches, lengths, last, temps, key):
            def body(i, carry):
                caches, lengths, last, key, toks = carry
                positions = jnp.minimum(lengths, maxlen - 1)
                logits, caches = token_decode_step(
                    model, w, last, positions, caches, maxlen
                )
                caches = _constrain_all(caches)
                key, sub = jax.random.split(key)
                sampled = _sample_dynamic(
                    logits, sub, temps, self.top_k, self.top_p
                )
                lengths = _vec(jnp.minimum(lengths + 1, maxlen))
                toks = toks.at[i].set(sampled)
                return caches, lengths, _vec(sampled), key, toks

            toks0 = jnp.zeros((k_window, self.num_slots), jnp.int32)
            caches, lengths, last, key, toks = jax.lax.fori_loop(
                0, k_window, body, (caches, lengths, last, key, toks0)
            )
            return caches, lengths, last, key, toks

        # the fixed program set: ONE decode window + one prefill per
        # prompt bucket (p_lens/admit/new_temps ride as traced vectors,
        # so only the bucket SHAPE triggers a compile)
        self._init_jit = jax.jit(init_state)
        self._prefill_jit = jax.jit(
            prefill, donate_argnums=(1, 2, 3, 4, 9)
        )  # args: w, caches, lengths, last, temps, rows, p_lens,
        #         admit, new_temps, key
        self._decode_jit = jax.jit(decode, donate_argnums=(1, 2, 3, 5))

        self.refresh_weights()
        self._caches, self._lengths, self._last, self._temps = (
            self._init_jit()
        )
        self._key = self._stage(
            np.asarray(jax.random.PRNGKey(self._seed))
        )

    # -- device staging ------------------------------------------------

    def _stage(self, arr):
        """Host value → device, replicated under the mesh (gang-safe)."""
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import put_global

        return put_global(np.asarray(arr), NamedSharding(self.mesh, P()))

    def _host(self, leaf) -> np.ndarray:
        if self.mesh is None:
            return np.asarray(leaf)
        from elephas_tpu.parallel.mesh import host_read

        return host_read(leaf, self.mesh)

    def refresh_weights(self) -> None:
        """(Re-)upload the model's weights — call after further
        training; the compiled programs take them as arguments, so no
        recompile happens."""
        import jax.numpy as jnp

        if self.mesh is None:
            self._weights = {
                v.path: jnp.asarray(v.value) for v in self.model.variables
            }
            return
        from elephas_tpu.models.transformer import _decode_shardings
        from elephas_tpu.parallel.mesh import put_global

        var_sh = _decode_shardings(
            list(self.model.variables), self.mesh, self.model_axis,
            self._rules,
        )
        self._weights = {
            v.path: put_global(np.asarray(v.value), s)
            for v, s in zip(self.model.variables, var_sh)
        }

    # -- request API ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, eos_id: int | None = None,
               on_token=None) -> Request:
        """Queue one generation request (admitted at the next step —
        submission is legal at any time, including mid-flight). Every
        gang process must submit the identical sequence of requests.
        ``on_token(token, done)`` streams tokens to the caller as they
        land; a raising callback fails only ITS request (``req.error``
        set, KV slot reclaimed) — the engine keeps serving."""
        prompt = np.asarray(prompt).reshape(-1)
        p = len(prompt)
        if p < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} < 1")
        if p + max_new_tokens > self.maxlen:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model's maxlen ({self.maxlen})"
            )
        if temperature < 0:
            raise ValueError(f"temperature={temperature} < 0")
        # fail HERE, not mid-flight in the prefill wave (where the
        # request would already hold a leased slot): a custom bucket
        # ladder may top out below the model's maxlen
        self.scheduler.bucket_for(p)
        req = self.scheduler.make_request(
            prompt, max_new_tokens, temperature=temperature, eos_id=eos_id,
            on_token=on_token,
        )
        req.submit_time = time.perf_counter()
        self.scheduler.submit(req)
        return req

    def _emit(self, req: Request, token: int) -> bool:
        """Record one generated token; reclaim + file the request when
        it finished. Returns done.

        A raising per-token callback fails the request CLEANLY: before
        this guard, the exception unwound through step() after the
        scheduler had recorded the token but before reclaim, leaking
        the KV slot for the engine's lifetime."""
        self.total_generated += 1
        slot = req.slot
        done = self.scheduler.on_token(slot, token)
        if req.on_token is not None:
            try:
                req.on_token(token, done)
            except Exception as e:
                req.error = e
                req.done = True
                done = True
                logger.warning(
                    "request %d failed in its on_token callback (%r) — "
                    "slot %d reclaimed, engine continues", req.rid, e, slot,
                )
        if done:
            req.finish_time = time.perf_counter()
            self.scheduler.reclaim(slot)
            self.finished_count += 1
            self.finished[req.rid] = req
            while len(self.finished) > self._finished_bound:
                self.finished.pop(next(iter(self.finished)))
        return done

    def _stage_slots(self, arr):
        """Host ``[num_slots, ...]`` value → device, slot axis over the
        batch axes (gang-safe)."""
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import put_global

        spec = (self.batch_axes,) + (None,) * (np.ndim(arr) - 1)
        return put_global(
            np.asarray(arr), NamedSharding(self.mesh, P(*spec))
        )

    def _prefill_wave(self, admitted: list[Request]) -> None:
        """Prefill one admission wave: ONE program launch per prompt
        bucket covers every request of that bucket in the wave."""
        by_bucket: dict[int, list[Request]] = {}
        for req in admitted:
            b = self.scheduler.bucket_for(len(req.prompt))
            by_bucket.setdefault(b, []).append(req)
        for bucket in sorted(by_bucket):
            reqs = by_bucket[bucket]
            rows = np.zeros((self.num_slots, bucket), np.int32)
            p_lens = np.ones((self.num_slots,), np.int32)
            admit = np.zeros((self.num_slots,), bool)
            new_temps = np.zeros((self.num_slots,), np.float32)
            for req in reqs:
                rows[req.slot, : len(req.prompt)] = req.prompt
                p_lens[req.slot] = len(req.prompt)
                admit[req.slot] = True
                new_temps[req.slot] = req.temperature
            (self._caches, self._lengths, self._last, self._temps,
             self._key, firsts) = self._prefill_jit(
                self._weights, self._caches, self._lengths, self._last,
                self._temps, self._stage_slots(rows),
                self._stage_slots(p_lens), self._stage_slots(admit),
                self._stage_slots(new_temps), self._key,
            )
            toks = self._host(firsts)
            for req in reqs:
                self._emit(req, int(toks[req.slot]))

    def step(self) -> list[tuple[Request, int, bool]]:
        """One engine iteration: admission+prefill of waiting requests
        into free slots, then one arena-wide decode window of
        ``steps_per_sync`` steps. Returns ``(request, token, done)``
        triples in generation order (a request can appear several
        times: its prefill token plus one per window position); the
        ``done`` flag is per-TOKEN — True only on a request's final
        token, so stream consumers can stop at it without dropping
        tokens."""
        emitted: list[tuple[Request, int, bool]] = []
        admitted = self.scheduler.admit()
        if admitted:
            self._prefill_wave(admitted)
            # before any decode token, so req.done here is the prefill
            # token's own flag
            emitted.extend(
                (req, req.tokens[-1], req.done) for req in admitted
            )
        if not self.scheduler.active:
            return emitted
        (self._caches, self._lengths, self._last, self._key,
         window) = self._decode_jit(
            self._weights, self._caches, self._lengths, self._last,
            self._temps, self._key,
        )
        toks = self._host(window)  # [steps_per_sync, num_slots]
        for i in range(self.steps_per_sync):
            if not self.scheduler.active:
                break  # window tail decoded garbage for empty slots
            self.scheduler.note_step()
            for slot, req in sorted(self.scheduler.active.items()):
                done = self._emit(req, int(toks[i, slot]))
                emitted.append((req, req.tokens[-1], done))
        return emitted

    def stream(self):
        """Drive the engine until the queue drains, yielding
        ``(request_id, token, done)`` as tokens land — the per-request
        token stream. More requests may be submitted while consuming
        (they join the next admission wave)."""
        while self.scheduler.has_work:
            for req, token, done in self.step():
                yield req.rid, token, done

    def run(self, requests=None) -> dict[int, np.ndarray]:
        """Convenience batch driver: optionally submit ``requests``
        (an iterable of ``(prompt, max_new_tokens)`` pairs or kwargs
        dicts), drive the engine until idle, and return
        ``{request_id: full token sequence (prompt + generated)}``."""
        if requests is not None:
            for r in requests:
                if isinstance(r, dict):
                    self.submit(**r)
                else:
                    prompt, max_new = r
                    self.submit(prompt, max_new)
        drained: dict[int, np.ndarray] = {}
        while self.scheduler.has_work:
            for req, _tok, done in self.step():
                if done:
                    drained[req.rid] = np.asarray(
                        req.full_sequence, np.int32
                    )
        return drained

    # -- introspection -------------------------------------------------

    def compile_stats(self) -> dict:
        """Compiled-program counts (the compile-count introspection
        hook): after warmup ``decode_compiles`` must stay at 1 for the
        server's whole life, and ``prefill_compiles`` is bounded by the
        bucket ladder."""

        def n(f):
            try:
                return int(f._cache_size())
            except Exception:  # pragma: no cover - jax-version drift
                return -1

        return {
            "decode_compiles": n(self._decode_jit),
            "prefill_compiles": n(self._prefill_jit),
            "buckets": tuple(self.scheduler.buckets),
        }

    def stats(self) -> dict:
        """Serving counters for the bench: aggregate generated tokens,
        decode steps, mean slot occupancy, and per-request latencies
        (seconds) of finished requests."""
        lat = [
            r.finish_time - r.submit_time
            for r in self.finished.values()
            if r.finish_time is not None and r.submit_time is not None
        ]
        return {
            "total_generated": self.total_generated,
            "decode_steps": self.scheduler._steps,
            "occupancy": self.scheduler.occupancy,
            "latencies": lat,
            "finished": self.finished_count,
            "num_slots": self.num_slots,
        }
