"""InferenceEngine — the continuous-batching serving loop.

One engine wraps one causal LM (a ``transformer_lm``-style FlashMHA
model) and serves any number of generation requests through two
program FAMILIES, compiled once each and reused for the life of the
server:

- one **prefill** program per prompt-length bucket (a closed, fixed
  ladder — :func:`~elephas_tpu.serving.scheduler.default_buckets`),
  writing a whole prompt's K/V into a leased slot in a single
  full-sequence forward;
- ONE **decode step** over the whole slot arena, advancing every
  in-flight sequence by one token at its own position (the vector
  write-cursor in :mod:`~elephas_tpu.serving.kv_cache`).

Each :meth:`InferenceEngine.step`: admit waiting requests into free
slots (prefill each), run the decode step, read the sampled tokens,
reclaim slots that hit EOS / their token budget. Requests can be
submitted at ANY time — they join the next step's admission wave
(iteration-level scheduling) — and finished slots free mid-flight, so
short sequences never hold long ones hostage the way one-shot batch
``generate()`` does.

Mesh-aware like the one-shot path: under a DP mesh the slot axis
shards over the batch axes; under TP the weights stay sharded through
``stateless_call`` with the planner's layouts and the arena shards
heads over the model axis. Every gang process must drive the engine
with the identical submission sequence (the SPMD contract ``generate``
already imposes); all read identical tokens.

Weights ride as jit ARGUMENTS, uploaded once at construction —
:meth:`refresh_weights` re-uploads after further training.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from elephas_tpu import telemetry
from elephas_tpu.ops.flash_serving import span_bucket_for, span_buckets
from elephas_tpu.serving.blocks import BlockAllocator
from elephas_tpu.serving.kv_quant import (
    check_kv_dtype,
    quantize_rows_np,
)
from elephas_tpu.serving.kv_cache import (
    SlotKVCache,
    chunked_prefill_forward,
    prefill_forward,
    prefix_copy,
    token_decode_step,
    verify_forward,
)
from elephas_tpu.serving.paged_kv import (
    PagedKVPool,
    blocks_for,
    gather_blocks,
    paged_chunk_forward,
    paged_token_decode_step,
    paged_verify_forward,
    scatter_blocks,
    table_bucket_for,
    table_buckets,
)
from elephas_tpu.serving.policy import (
    DEFAULT_TENANT,
    AdmissionRejected,
    Policy,
)
from elephas_tpu.serving.speculative import (
    AcceptanceThrottle,
    resolve_drafter,
)
from elephas_tpu.serving.scheduler import (
    Admission,
    Request,
    Scheduler,
    default_buckets,
)

logger = logging.getLogger(__name__)


class RequestCancelled(RuntimeError):
    """Set as ``req.error`` when :meth:`InferenceEngine.cancel`
    reclaims an in-flight request (ISSUE 14): the request is ``done``
    without completing, its tokens-so-far kept for the caller."""


class _OffloadRecord:
    """Host-side K/V of a preempted request: dense block rows per
    layer plus the cursor state needed for a bit-exact resume. Rows
    are tuples of numpy arrays at the arena's STORED dtype — fp
    ``(k, v)`` pairs, or quantized ``(kq, vq, k_scale, v_scale)``
    4-tuples (ISSUE 19: offloaded blocks stay quantized on host, so
    the record is ~4x/~7x smaller and the resume round-trip is
    bitwise within the dtype)."""

    __slots__ = ("rows", "n_blocks", "cur_len")

    def __init__(self, rows, n_blocks, cur_len):
        self.rows = rows
        self.n_blocks = int(n_blocks)
        self.cur_len = int(cur_len)

    def nbytes(self) -> int:
        return sum(
            a.nbytes for leaves in self.rows.values() for a in leaves
        )


def _sample_dynamic(logits, key, temps, top_k, top_p):
    """Per-row sampling with a DYNAMIC temperature vector: rows with
    ``temps <= 0`` take greedy argmax (bit-identical to the one-shot
    path's temperature-0 branch), the rest temperature-scaled
    categorical under the engine's static top_k/top_p filters (same
    filter math as ``_sample_logits``)."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.models.transformer import _filter_logits

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _filter_logits(
        logits / jnp.maximum(temps, 1e-6)[:, None], top_k, top_p
    )
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


class InferenceEngine:
    """Continuous-batching server over a slot-based KV cache.

    ``num_slots`` bounds concurrent in-flight sequences (rounded up to
    the mesh's batch-axis product so the arena shards evenly);
    ``buckets`` overrides the prompt-padding ladder; ``top_k`` /
    ``top_p`` are engine-static sampling filters; per-request
    ``temperature`` rides as data (0 = greedy).

    ``prefix_cache=True`` (ISSUE 4) keeps finished requests' prompt
    K/V resident as donor slots under a deterministic radix index:
    a new request sharing a prompt prefix pays one slot-to-slot copy
    plus suffix-only prefill instead of recomputing the prefix.
    ``prefill_chunk=c`` splits prefill into ``c``-token chunks run
    under a per-step token budget (``prefill_budget``, default one
    chunk) BETWEEN decode windows, so a long prompt arrival no longer
    stalls every in-flight request's next token. Both compose; both
    keep the compiled shape set closed (``compile_stats()``). Chunk
    boundaries consume PRNG key splits, so temp>0 sampling streams
    differ from the unchunked engine (still deterministic per
    configuration); temperature-0 tokens are exact either way.

    ``paged=True`` (ISSUE 7) swaps the fixed arena for the paged
    block pool (``block_size=``, ``num_blocks=``): per-request block
    reservations instead of per-slot maxlen rows, copy-free prefix
    sharing by refcount when ``prefix_cache=True``, and — with
    ``preemption=True`` — priority-ordered preempt → host-offload →
    resume under pool pressure (bit-exact on resume). A request that
    can never fit the pool is rejected gracefully at ``submit()``
    (``req.error``) instead of wedging the queue. Compiled shapes
    stay a closed set: one decode program per block-table bucket,
    one chunk program per (width, table bucket).

    ``speculative=True`` (ISSUE 8) decodes draft-and-verify: a cheap
    drafter (``spec_drafter``: ``"ngram"`` prompt-lookup by default, or
    a small draft model / custom :class:`~elephas_tpu.serving.\
speculative.Drafter`) proposes up to ``spec_k`` tokens per slot and ONE
    batched verify forward scores them all, accepting the longest
    greedy-matching prefix plus a bonus token — several tokens per
    target forward, bit-exact at temperature 0 (temp>0 streams diverge
    from plain decode like chunked prefill: deterministic per config,
    differently keyed). A per-request acceptance throttle falls back to
    plain decode when drafts stop landing and re-probes periodically.
    Works on both arenas; one verify program per window width (fixed)
    or (width, table bucket) pair (paged) keeps the shape set closed.

    ``policy=`` (ISSUE 10) installs an SLO admission policy
    (:mod:`~elephas_tpu.serving.policy`): per-tenant token-weighted
    fair share, deadline-EDF ordering with aging, overload admission
    control (loud :class:`~elephas_tpu.serving.policy.\
AdmissionRejected` at submit), policy-derived preemption priority, and
    tenant-labeled telemetry + SLO-attainment counters. The policy
    reorders and rejects — it NEVER touches decoding, so temperature-0
    token streams stay bit-exact per request under any policy.

    ``attention="flash"`` (ISSUE 11, the default) runs every serving
    program's attention core through the tiled online-softmax kernel
    (:mod:`elephas_tpu.ops.flash_serving`): full-bucket prefill skips
    strictly-future tiles statically, chunk/verify stream the arena
    row in tiles, and the fixed arena's decode/chunk attend over a
    SPAN BUCKET covering the live residents instead of ``maxlen``
    (compiled per touched bucket — a closed ladder). ``"naive"``
    selects the seed full-materialized path, kept as the bitwise
    parity oracle. Flash logits match naive to float tolerance;
    temperature-0 token streams are exact (see docs/API.md).

    ``flight_recorder=`` (ISSUE 12) bounds the per-request **flight
    recorder**: the engine assembles a structured lifecycle record for
    every request (admission verdict + queue wait, admission kind and
    reuse length, prefill chunks, preempt/resume, spec rounds, per-
    token step indices, finish reason) and keeps the last N finished
    ones queryable via :meth:`explain` (and the gateway's
    ``GET /v1/requests/{rid}/trace``). ``0``/``None`` — or
    construction under telemetry null mode — turns recording off
    entirely (:meth:`explain` then raises, loudly). Records are
    ordered by scheduler steps and tracer sequence numbers; wall time
    appears only in export-only fields, so recording never perturbs
    the gang-deterministic schedule.

    ``sp_prefill=`` (ISSUE 11, paged + unmeshed engines) arms
    sequence-parallel long-prompt prefill: a cold prompt of at least
    ``sp_threshold`` tokens (default ``maxlen // 2``) runs ONE
    ring/Ulysses-sharded forward over the given mesh's ``sp_axis``,
    lands its K/V straight into the slot's reserved pool blocks, and
    decodes unmeshed — removing the single-device ceiling on prompt
    ingestion (``sp_mechanism="ring"`` has no head-count constraint;
    ``"ulysses"`` needs ``num_heads % axis_size == 0``).

    Pipeline parallelism lives in its own engine (ISSUE 15):
    :class:`~elephas_tpu.serving.pp_engine.PPEngine` runs continuous
    batching over a PP×TP mesh with per-stage paged KV pools and
    microbatched decode waves — construct THIS engine via
    ``SparkModel.serve()`` on a DP/TP mesh (or directly on no mesh),
    and the PP engine when model depth no longer fits one chip group.
    """

    def __init__(self, model, num_slots: int = 8, mesh=None,
                 batch_axes=("data",), model_axis=None, rules=None,
                 top_k: int | None = None, top_p: float | None = None,
                 seed: int = 0, buckets=None, steps_per_sync: int = 1,
                 prefix_cache: bool = False,
                 prefix_min_reuse: int = 1,
                 prefill_chunk: int | None = None,
                 prefill_budget: int | None = None,
                 paged: bool = False,
                 block_size: int | None = None,
                 num_blocks: int | None = None,
                 preemption: bool = False,
                 kv_dtype: str = "fp",
                 speculative: bool = False,
                 spec_k: int | None = None,
                 spec_drafter=None,
                 policy=None,
                 attention: str = "flash",
                 sp_prefill=None,
                 sp_axis: str = "seq",
                 sp_threshold: int | None = None,
                 sp_mechanism: str = "ring",
                 flight_recorder: int | None = 256):
        import jax
        import jax.numpy as jnp

        from elephas_tpu.models.transformer import (
            validate_token_decode_model,
        )

        flash_layers, _stock, _gqa = validate_token_decode_model(
            model,
            what="the serving engine",
            hint="use one-shot generate()",
            allow_stock=False,
        )
        self.model = model
        self.maxlen = int(model.inputs[0].shape[1])
        self.vocab = int(model.outputs[0].shape[-1])
        self.top_k = top_k
        self.top_p = top_p
        if top_k is not None and not 0 < int(top_k) <= self.vocab:
            raise ValueError(
                f"top_k={top_k} outside (0, vocab={self.vocab}]"
            )
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p={top_p} outside (0, 1]")

        self.mesh = mesh
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        self.batch_axes = tuple(batch_axes)
        self.model_axis = model_axis
        if mesh is not None:
            missing = [a for a in self.batch_axes if a not in mesh.shape]
            if missing:
                raise ValueError(
                    f"batch_axes {missing} not in mesh axes "
                    f"{tuple(mesh.shape)}"
                )
            dp = int(
                np.prod([mesh.shape[a] for a in self.batch_axes])
            )
            if num_slots % dp:
                rounded = num_slots + (-num_slots) % dp
                logger.info(
                    "rounding num_slots %d -> %d (multiple of the "
                    "batch-axis product %d)", num_slots, rounded, dp,
                )
                num_slots = rounded
        if num_slots < 1:
            raise ValueError(f"num_slots={num_slots} < 1")
        self.num_slots = int(num_slots)

        if buckets is not None:
            buckets = tuple(int(b) for b in buckets)
            bad = [b for b in buckets if not 0 < b <= self.maxlen]
            if bad:
                raise ValueError(
                    f"buckets {bad} outside (0, maxlen={self.maxlen}] — "
                    f"a bucket beyond maxlen would overflow the KV arena"
                )

        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if not 0 < prefill_chunk <= self.maxlen:
                raise ValueError(
                    f"prefill_chunk={prefill_chunk} outside "
                    f"(0, maxlen={self.maxlen}]"
                )
        self.prefill_chunk = prefill_chunk
        if prefill_budget is not None:
            if prefill_chunk is None:
                raise ValueError(
                    "prefill_budget requires prefill_chunk — without "
                    "chunking, prefill is a single blocking wave and "
                    "the budget would be silently ignored"
                )
            if int(prefill_budget) < 1:
                raise ValueError(f"prefill_budget={prefill_budget} < 1")
        # per-step() prefill token budget (chunked mode): default one
        # chunk's worth — the typical long-prompt arrival streams in at
        # one chunk per decode window, bounding in-flight inter-token
        # latency at roughly one chunk of extra compute
        self._prefill_budget = (
            int(prefill_budget) if prefill_budget is not None
            else (prefill_chunk or 0)
        )

        # -- paged arena knobs (ISSUE 7) -------------------------------
        self.paged = bool(paged)
        if not self.paged:
            if block_size is not None or num_blocks is not None:
                raise ValueError(
                    "block_size/num_blocks require paged=True — the "
                    "fixed arena has no blocks, silently ignoring the "
                    "knobs would misreport capacity"
                )
            if preemption:
                raise ValueError(
                    "preemption requires paged=True — the fixed arena "
                    "has no block pool to swap out of"
                )
            self.block_size = None
            self.num_blocks = None
        else:
            bs = 16 if block_size is None else int(block_size)
            if not 0 < bs <= self.maxlen:
                raise ValueError(
                    f"block_size={bs} outside (0, maxlen={self.maxlen}]"
                )
            self.block_size = bs
            # blocks any single request may need (full maxlen context)
            self.max_blocks_per_slot = blocks_for(self.maxlen, bs)
            # default pool: capacity parity with the fixed arena —
            # every slot could still hold a full-maxlen context; the
            # paged win is that short requests stop RESERVING that
            nb = (
                int(num_blocks) if num_blocks is not None
                else self.num_slots * self.max_blocks_per_slot
            )
            if nb < 1:
                raise ValueError(f"num_blocks={nb} < 1")
            self.num_blocks = nb
            self._tbuckets = table_buckets(self.max_blocks_per_slot)
        self.preemption = bool(preemption)

        # -- quantized paged KV (ISSUE 19) -----------------------------
        # "fp" (default) stores f32 pool blocks — the parity oracle,
        # bit-for-bit the historical engine. "int8"/"int4" store
        # quantized codes + per-(position, head) f32 scales: quantize
        # on write inside the paged programs, dequantize inside the
        # flash span tiles (kv_quant module). Temp-0 exactness holds
        # WITHIN a dtype (offload/resume/migration move quantized
        # blocks bit-identically); cross-dtype quality is gated
        # against the fp oracle (docs/API.md "Quantized KV").
        check_kv_dtype(kv_dtype)
        if kv_dtype != "fp" and not self.paged:
            raise ValueError(
                "kv_dtype requires paged=True — the fixed slot arena "
                "has no quantized storage path; silently serving fp "
                "would misreport the KV byte budget"
            )
        self.kv_dtype = kv_dtype

        # -- speculative decoding knobs (ISSUE 8) ----------------------
        self.speculative = bool(speculative)
        if not self.speculative:
            if spec_k is not None or spec_drafter is not None:
                raise ValueError(
                    "spec_k/spec_drafter require speculative=True — "
                    "silently ignoring the knobs would misreport how "
                    "the engine decodes"
                )
            self.spec_k = None
        else:
            k = 4 if spec_k is None else int(spec_k)
            # the verify window feeds 1 (last token) + k drafts; its
            # widest write lands at position cursor + k, capped by the
            # per-slot draft budget at maxlen - 1 — k itself only needs
            # to leave room for at least one real position
            if not 1 <= k < self.maxlen:
                raise ValueError(
                    f"spec_k={k} outside [1, maxlen={self.maxlen})"
                )
            self.spec_k = k

        # -- attention kernel selection (ISSUE 11) ---------------------
        # "flash" (default) = tiled online-softmax serving programs
        # (ops/flash_serving): O(span) score memory, static causal tile
        # skipping in full-bucket prefill, span-bucketed block-span
        # reads in fixed-arena decode/chunk. "naive" = the seed
        # full-materialized einsum/softmax path, kept selectable as the
        # bitwise parity oracle. Flash output matches naive to float
        # tolerance and temp-0 token streams exactly (documented in
        # docs/API.md "Attention kernels").
        if attention not in ("flash", "naive"):
            raise ValueError(
                f"attention must be 'flash' or 'naive', got "
                f"{attention!r}"
            )
        self.attention = attention
        # fixed-arena span ladder: flash decode/chunk/verify programs
        # attend over cache[:, :span] for a bucketed span covering the
        # live residents — compiled once per touched bucket (a closed
        # set; the floor keeps small models at ONE decode compile)
        self._sbuckets = span_buckets(self.maxlen)

        # -- sequence-parallel long-prompt prefill (ISSUE 11) ----------
        if sp_prefill is not None:
            if not self.paged:
                raise ValueError(
                    "sp_prefill requires paged=True — the SP prefill "
                    "lands K/V into the block pool (the fixed arena "
                    "has no block-granular landing path)"
                )
            if mesh is not None:
                raise ValueError(
                    "sp_prefill requires an UNMESHED engine — the SP "
                    "mesh serves prefill only, and decode proceeds "
                    "unmeshed on the landed blocks (a decode mesh "
                    "would double-shard the pool)"
                )
            if sp_mechanism not in ("ring", "ulysses"):
                raise ValueError(
                    f"sp_mechanism must be 'ring' or 'ulysses', got "
                    f"{sp_mechanism!r}"
                )
            if sp_axis not in sp_prefill.shape:
                raise ValueError(
                    f"sp_axis {sp_axis!r} not in the SP mesh axes "
                    f"{tuple(sp_prefill.shape)}"
                )
            sp_w = int(sp_prefill.shape[sp_axis])
            if sp_w & (sp_w - 1):
                # pad lengths are powers of two (sp_pad_len), and a
                # non-power-of-two shard count divides none of them —
                # the shard_map would raise mid-serve on the first
                # long prompt; fail HERE instead
                raise ValueError(
                    f"sp_prefill axis {sp_axis!r} has size {sp_w} — "
                    f"SP prefill pads prompts to power-of-two "
                    f"lengths, which only tile over a power-of-two "
                    f"shard count; reshape the mesh"
                )
            if sp_mechanism == "ulysses":
                bad = [
                    (name, h) for name, h, _d in (
                        (l.name, int(l.num_heads), int(l.head_dim))
                        for l in flash_layers
                    ) if h % sp_w
                ]
                if bad:
                    raise ValueError(
                        f"ulysses SP prefill needs num_heads divisible "
                        f"by the seq axis size ({sp_w}); offending "
                        f"layers: {bad} — use sp_mechanism='ring'"
                    )
            if sp_threshold is not None and int(sp_threshold) < 1:
                raise ValueError(
                    f"sp_threshold={sp_threshold} < 1"
                )
        elif sp_threshold is not None or sp_axis != "seq" \
                or sp_mechanism != "ring":
            raise ValueError(
                "sp_threshold/sp_axis/sp_mechanism require sp_prefill= "
                "(an SP mesh) — silently ignoring them would misreport "
                "how long prompts prefill"
            )
        self.sp_mesh = sp_prefill
        self.sp_axis = sp_axis
        self.sp_mechanism = sp_mechanism
        # prompts at or above the threshold prefill over the SP mesh;
        # default: half the model's context (the regime where a single
        # device's prefill dominates TTFT)
        self.sp_threshold = (
            int(sp_threshold) if sp_threshold is not None
            else max(1, self.maxlen // 2)
        ) if sp_prefill is not None else None

        # -- SLO admission policy (ISSUE 10) ---------------------------
        if policy is not None and not isinstance(policy, Policy):
            raise TypeError(
                f"policy must be a serving.policy.Policy (or None), "
                f"got {type(policy).__name__} — build one with "
                f"FairSharePolicy(tenants=...) or resolve_policy()"
            )
        self.policy = policy

        if self.paged:
            self.arena = PagedKVPool(
                flash_layers, self.num_blocks, self.block_size,
                mesh=mesh, batch_axes=self.batch_axes,
                model_axis=model_axis, kv_dtype=self.kv_dtype,
            )
        else:
            self.arena = SlotKVCache(
                flash_layers, self.num_slots, self.maxlen,
                mesh=mesh, batch_axes=self.batch_axes,
                model_axis=model_axis,
            )
        # -- telemetry identity captured EARLY so the allocator's gauge
        # shares the engine's label set (release_telemetry retires them
        # together); the metric definitions follow below
        treg = telemetry.registry()
        self._telemetry_registry = treg
        self._tracer = telemetry.tracer()
        eid = telemetry.instance_label()
        self.telemetry_label = eid
        # -- per-request flight recorder + compile watching (ISSUE 12):
        # both captured at construction like the registry/tracer, so an
        # engine built under null mode stays zero-overhead for life.
        # _flight_live holds in-flight records (rid -> dict); finished
        # lifecycles move into the bounded FlightRecorder ring.
        if flight_recorder is not None and int(flight_recorder) < 0:
            raise ValueError(
                f"flight_recorder={flight_recorder} < 0 — use 0/None "
                f"to disable, or a positive record capacity"
            )
        fr_capacity = 0 if flight_recorder is None else int(flight_recorder)
        self._flight = (
            telemetry.FlightRecorder(fr_capacity)
            if fr_capacity and not telemetry.null_mode() else None
        )
        self._flight_live: dict[int, dict] = {}
        # jit-compile spans: each dispatch that grows a program's jit
        # cache is recorded as a named "jit.compile" span, so a
        # mid-serve recompile shows up ON the request timeline instead
        # of being reconstructed by hand (the PR-9 light-tenant TTFT
        # forensics). Off under null mode — the cache-size probe is
        # cheap, but null means null.
        self._trace_compiles = not telemetry.null_mode()

        allocator = None
        if self.paged:
            allocator = BlockAllocator(
                self.num_blocks, self.block_size,
                free_gauge=treg.gauge(
                    "elephas_serving_blocks_free",
                    "Unleased KV pool blocks (paged arena)",
                    labels=("engine",),
                ).labels(engine=eid),
            )
        self.scheduler = Scheduler(
            self.num_slots, buckets or default_buckets(self.maxlen),
            prefix_cache=prefix_cache,
            prefix_min_reuse=prefix_min_reuse,
            allocator=allocator,
            preemption=preemption,
            policy=policy,
        )
        self._rules = rules
        self._seed = int(seed)
        # slots mid-chunked-prefill: slot -> [Admission, progress]
        # (progress = prompt tokens already resident, incl. any copied
        # prefix; the slot joins decode only once progress == len(prompt))
        self._prefilling: dict[int, list] = {}
        # slots whose in-flight prefill straddled a weight refresh —
        # their rows mix weight generations and never become donors
        self._stale_prefill: set[int] = set()
        # completed requests, BOUNDED: a server alive for millions of
        # requests must not grow host memory linearly — callers keep
        # their own Request handles from submit(); this registry only
        # feeds stats()/tests and evicts oldest past the bound
        self.finished: dict[int, Request] = {}
        self._finished_bound = 4096
        self._protected: set[int] = set()
        # warning cadence for _evict_finished: a PLAIN count, never the
        # registry counter (which reads 0 under telemetry null mode)
        self._evictions_seen = 0

        # -- telemetry (ISSUE 5): the registry/tracer captured above
        # are the engine's for life, so an engine built under null mode
        # stays ~zero-overhead even if the global flag flips later.
        # Counters are report-only views (`total_generated` etc. read
        # them back); nothing below drives control flow.
        def _c(name, help_):
            return treg.counter(
                name, help_, labels=("engine",)
            ).labels(engine=eid)

        self._m_tokens = _c(
            "elephas_serving_tokens_generated_total",
            "Generated tokens emitted by the serving engine",
        )
        self._m_finished = _c(
            "elephas_serving_requests_finished_total",
            "Requests that completed (EOS or token budget)",
        )
        self._m_finished_evicted = _c(
            "elephas_serving_finished_evicted_total",
            "Finished requests evicted from the bounded result registry "
            "before the caller consumed them",
        )
        self._m_decode_windows = _c(
            "elephas_serving_decode_windows_total",
            "Arena-wide decode window dispatches",
        )
        self._m_prefill_stalls = _c(
            "elephas_serving_prefill_stall_slots_total",
            "Mid-prefill slots deferred to a later step because the "
            "per-step chunk-token budget was exhausted",
        )
        self._m_ttft = treg.histogram(
            "elephas_serving_ttft_seconds",
            "Submit-to-first-token latency of served requests",
            labels=("engine",),
        ).labels(engine=eid)
        self._m_itl = treg.histogram(
            "elephas_serving_inter_token_seconds",
            "Arrival gap between consecutive tokens of one request",
            labels=("engine",),
        ).labels(engine=eid)
        # paged-arena accounting (ISSUE 7): counters exist in BOTH
        # modes so stats() keys never vary by config — the fixed arena
        # simply never increments them
        self._m_preemptions = _c(
            "elephas_serving_preemptions_total",
            "Requests preempted (blocks offloaded to host) so a "
            "higher-priority arrival could admit",
        )
        self._m_resumes = _c(
            "elephas_serving_resumes_total",
            "Preempted requests restored from host offload",
        )
        self._m_offload_blocks = _c(
            "elephas_serving_offloaded_blocks_total",
            "KV pool blocks swapped to host memory by preemption",
        )
        self._m_rejected = _c(
            "elephas_serving_rejected_total",
            "Requests rejected at submit because prompt + "
            "max_new_tokens can never fit the block pool",
        )
        # speculative decoding (ISSUE 8): counters exist in BOTH modes
        # (keys in stats() never vary by config); a non-speculative
        # engine simply never increments them
        self._m_spec_drafted = _c(
            "elephas_serving_spec_draft_tokens_total",
            "Drafted tokens scored by the speculative verify forward",
        )
        self._m_spec_accepted = _c(
            "elephas_serving_spec_accepted_tokens_total",
            "Drafted tokens accepted by the longest-matching-prefix "
            "rule (each saved one target-model decode step)",
        )
        self._m_spec_rounds = _c(
            "elephas_serving_spec_verify_rounds_total",
            "Batched speculative verify dispatches",
        )
        self._m_spec_throttled = _c(
            "elephas_serving_spec_throttled_total",
            "Times a request's collapsed acceptance rate tripped the "
            "drafting throttle (fell back to plain decode)",
        )
        # SLO scheduling (ISSUE 10): policy admission rejects (distinct
        # from the paged never-fits counter — this one is load shed,
        # not a capacity impossibility), plus tenant-labeled series.
        # Families exist in EVERY mode so stats() keys never vary by
        # config; children materialize per tenant label on first use.
        self._m_admission_rejected = _c(
            "elephas_serving_admission_rejected_total",
            "Requests rejected at submit by the policy's overload "
            "admission control (429 on the gateway)",
        )
        # lifecycle control (ISSUE 14): cancellation + live migration.
        # Counters exist in every mode (stats() keys never vary by
        # config); engines outside a fleet simply never migrate.
        self._m_cancelled = _c(
            "elephas_serving_cancelled_total",
            "In-flight requests cancelled before completion "
            "(slot/blocks reclaimed; gateway client disconnects land "
            "here)",
        )
        self._m_migrated_out = _c(
            "elephas_serving_migrated_out_total",
            "Requests exported off this engine as migration records "
            "(fleet drain / rebalancing)",
        )
        self._m_migrated_in = _c(
            "elephas_serving_migrated_in_total",
            "Requests adopted from another replica's migration record",
        )
        # quantized KV + scoring (ISSUE 19): counters exist in EVERY
        # mode (stats() keys never vary by config) — fp engines count
        # fp-sized offload/export bytes, non-scoring callers simply
        # never increment score requests
        self._m_offload_bytes = _c(
            "elephas_serving_kv_quant_offload_bytes_total",
            "Host bytes written by preemption offload records (KV "
            "block rows + scales at the arena's stored kv_dtype)",
        )
        self._m_export_bytes = _c(
            "elephas_serving_kv_quant_export_bytes_total",
            "Payload bytes of migration/handoff export records "
            "(per-layer arrays at the stored kv_dtype, header "
            "excluded) — the counted wire-size the bench quant "
            "section gates on",
        )
        self._m_score_requests = _c(
            "elephas_serving_score_requests_total",
            "Completions scored through score() / POST /v1/score "
            "(one verify-style forward each, engine state untouched)",
        )

        def _tc(name, help_):
            return treg.counter(name, help_, labels=("engine", "tenant"))

        self._mf_tenant_tokens = _tc(
            "elephas_serving_tenant_tokens_total",
            "Generated tokens emitted, by tenant",
        )
        self._mf_tenant_admitted = _tc(
            "elephas_serving_tenant_admitted_total",
            "Requests admitted into KV slots, by tenant",
        )
        self._mf_tenant_rejected = _tc(
            "elephas_serving_tenant_rejected_total",
            "Requests rejected at submit, by tenant (admission "
            "control and paged never-fit alike)",
        )
        self._mf_slo_met = _tc(
            "elephas_serving_slo_met_total",
            "First tokens that landed within their declared TTFT "
            "deadline, by tenant",
        )
        self._mf_slo_missed = _tc(
            "elephas_serving_slo_missed_total",
            "First tokens that landed after their declared TTFT "
            "deadline, by tenant",
        )
        # per-tenant queue depth: callback gauges reading the live
        # scheduler queue — scrape and stats() see the same truth with
        # zero update plumbing (and zero chance of drift)
        self._mf_tenant_queue = treg.gauge(
            "elephas_serving_tenant_queue_depth",
            "Waiting requests queued, by tenant",
            labels=("engine", "tenant"),
        )
        if self.policy is not None:
            sched = self.scheduler
            for t in self.policy.tenant_names:
                self._mf_tenant_queue.labels(
                    engine=eid, tenant=t
                ).set_function(lambda t=t: sched.waiting_count(t))
                # materialize the zero-valued children now so a scrape
                # before the first request already shows every tenant
                for fam in (
                    self._mf_tenant_tokens, self._mf_tenant_admitted,
                    self._mf_tenant_rejected, self._mf_slo_met,
                    self._mf_slo_missed,
                ):
                    fam.labels(engine=eid, tenant=t)

        # attention-kernel info gauge (ISSUE 11): the kernel rides as a
        # LABEL (value is a constant 1) so dashboards can join "which
        # kernel is this engine on" against any of its other series
        treg.gauge(
            "elephas_serving_attn_kernel",
            "Attention kernel the serving programs run (info gauge: "
            "constant 1, kernel name in the label)",
            labels=("engine", "kernel"),
        ).labels(engine=eid, kernel=self.attention).set(1)
        # kv_dtype info gauge (ISSUE 19): same join-by-label idiom as
        # the kernel gauge — which storage dtype this arena speaks
        treg.gauge(
            "elephas_serving_kv_quant_mode",
            "KV storage dtype of the paged arena (info gauge: "
            "constant 1, dtype name in the label)",
            labels=("engine", "kv_dtype"),
        ).labels(engine=eid, kv_dtype=self.kv_dtype).set(1)
        # weight generation (ISSUE 20): plain value gauge (not an info
        # gauge — generations are ordered and dashboards graph the
        # fleet converging), re-set by every stamped refresh_weights()
        self.weight_version = 0
        self._g_weight_version = treg.gauge(
            "elephas_serving_weight_version",
            "Weight generation the engine currently serves "
            "(0 = unversioned; stamped by refresh_weights(version=))",
            labels=("engine",),
        ).labels(engine=eid)
        self._g_weight_version.set(self.weight_version)
        # per-bucket prefill-token histogram (ISSUE 11): one observation
        # per completed prefill, labeled by the compiled bucket it ran
        # through — Chrome traces say WHERE long prompts spend TTFT,
        # this says how often each bucket is actually exercised
        self._mf_prefill_tokens = treg.histogram(
            "elephas_serving_prefill_tokens",
            "Prompt tokens ingested per completed prefill, by prompt "
            "size class (the prompt-bucket ladder; sp<S> = sequence-"
            "parallel padded length). NOTE: chunked/paged prefills "
            "compile per chunk width, not per prompt bucket — this "
            "label classifies the PROMPT, not the program.",
            labels=("engine", "bucket"),
        )
        treg.gauge(
            "elephas_serving_slots", "KV-cache slots in the arena",
            labels=("engine",),
        ).labels(engine=eid).set(self.num_slots)
        treg.gauge(
            "elephas_serving_kv_arena_bytes",
            "Host-side size estimate of the full KV arena at its "
            "stored dtype (f32, or int8/int4 codes + scales)",
            labels=("engine",),
        ).labels(engine=eid).set(self.arena.nbytes())
        if self.paged:
            # named WITHOUT the _total suffix (ISSUE 12): OpenMetrics
            # reserves _total for counters, and this is a gauge — a
            # spec-strict scraper of the exemplar exposition would
            # reject the whole page over it (was
            # elephas_serving_blocks_total through PR 11)
            treg.gauge(
                "elephas_serving_kv_blocks",
                "KV pool blocks in the paged arena",
                labels=("engine",),
            ).labels(engine=eid).set(self.num_blocks)

        maxlen, arena = self.maxlen, self.arena

        def _constrain_all(caches):
            # leaf-generic over the entry arity: fp (k, v) pairs and
            # quantized (kq, vq, k_scale, v_scale) 4-tuples alike
            heads = {name: h for name, h, _d in arena.specs}
            return {
                name: tuple(
                    arena.constrain(z, heads[name]) for z in leaves
                )
                for name, leaves in caches.items()
            }

        def _vec(z):
            if mesh is None:
                return z
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(
                z, NamedSharding(mesh, P(self.batch_axes))
            )

        def init_state():
            caches = arena.init()
            lengths = _vec(jnp.zeros((self.num_slots,), jnp.int32))
            last = _vec(jnp.zeros((self.num_slots,), jnp.int32))
            temps = _vec(jnp.zeros((self.num_slots,), jnp.float32))
            return caches, lengths, last, temps

        attn_kernel = self.attention

        def prefill(w, caches, lengths, last, temps, tokens_rows,
                    p_lens, admit, new_temps, key):
            logits, caches = prefill_forward(
                model, w, tokens_rows, caches, admit, maxlen,
                attention=attn_kernel,
            )
            caches = _constrain_all(caches)
            # each row's next-token logits sit at its own prompt end —
            # one-hot contraction over the bucket axis (exact select,
            # and slot-local under the mesh unlike a per-row gather)
            S = tokens_rows.shape[1]
            at_end = (
                (p_lens - 1)[:, None] == jnp.arange(S)[None, :]
            ).astype(logits.dtype)
            last_logits = jnp.einsum("bs,bsv->bv", at_end, logits)
            key, sub = jax.random.split(key)
            firsts = _sample_dynamic(
                last_logits, sub, new_temps, self.top_k, self.top_p
            )
            lengths = _vec(jnp.where(admit, p_lens, lengths))
            last = _vec(jnp.where(admit, firsts, last))
            temps = _vec(jnp.where(admit, new_temps, temps))
            return caches, lengths, last, temps, key, firsts

        # multi-step scheduling (the vLLM/TensorRT-LLM trick): decode
        # `steps_per_sync` tokens per dispatch inside ONE fori_loop, so
        # program-launch + host-sync cost amortizes over the window.
        # Scheduling decisions (admission, reclaim) then happen at
        # window boundaries — k=1 is pure Orca iteration-level
        # scheduling; larger k trades up to k-1 wasted positions on a
        # mid-window finish for far fewer host round-trips. Greedy
        # (temperature-0) tokens are identical across k; sampled
        # streams match only while windows are fully consumed — a
        # drain that abandons a window tail still advanced the key k
        # times, so later temp>0 requests may sample differently than
        # under k=1 (deterministic per (seed, k, schedule) either way).
        k_window = max(1, int(steps_per_sync))
        self.steps_per_sync = k_window

        def decode(w, caches, lengths, last, temps, active, key,
                   span=None):
            # `active` masks idle / mid-chunked-prefill / prefix-donor
            # slots OUT of the cache write and cursor advance — their
            # resident rows must survive the window; active slots' math
            # is untouched (bit-identical to the unmasked program).
            # `span` (STATIC, flash mode): the attended row slice — a
            # span bucket covering every live resident + the window.
            def body(i, carry):
                caches, lengths, last, key, toks = carry
                positions = jnp.minimum(lengths, maxlen - 1)
                logits, caches = token_decode_step(
                    model, w, last, positions, caches, maxlen,
                    active=active, attention=attn_kernel, span=span,
                )
                caches = _constrain_all(caches)
                key, sub = jax.random.split(key)
                sampled = _sample_dynamic(
                    logits, sub, temps, self.top_k, self.top_p
                )
                lengths = _vec(jnp.where(
                    active, jnp.minimum(lengths + 1, maxlen), lengths
                ))
                toks = toks.at[i].set(sampled)
                last = _vec(jnp.where(active, sampled, last))
                return caches, lengths, last, key, toks

            toks0 = jnp.zeros((k_window, self.num_slots), jnp.int32)
            caches, lengths, last, key, toks = jax.lax.fori_loop(
                0, k_window, body, (caches, lengths, last, key, toks0)
            )
            return caches, lengths, last, key, toks

        def chunk_step(w, caches, lengths, last, temps, tokens, offs,
                       clens, act, fin, p_lens, new_temps,
                       src_idx, copy_mask, copy_len, key,
                       has_copy: bool, span=None):
            """One bounded prefill chunk for every slot in ``act`` —
            cold chunked prefill and post-copy suffix prefill alike.
            Slots in ``fin`` end their prompt inside this chunk: their
            first token samples from the prompt-end logits row and they
            join the decode population.

            Prefix-cache transplants FUSE into this program (``src_idx``
            / ``copy_mask`` / ``copy_len``; all-False mask = no copy,
            same compiled shape): a hit admission whose suffix prefills
            immediately pays ONE dispatch, not copy-then-chunk — on
            dispatch-bound backends the launch overhead rivals the tiny
            suffix compute itself. The standalone copy program below
            stays for chunked-queue admissions, where the copy must
            land while the wave still pins the donor but the first
            chunk call may be budget-deferred to a later step.

            ``has_copy`` is STATIC: the donor gather costs O(slots² ·
            maxlen · H · Dh) per layer whether or not the mask selects
            anything (the mask is runtime data XLA cannot elide), so
            copy-free calls — every budgeted chunk in chunked mode —
            trace a variant without it. Two entries per width at most,
            and each mode only ever uses one."""
            if has_copy:
                caches = _constrain_all(prefix_copy(
                    caches, src_idx, copy_mask, copy_len, maxlen
                ))
            logits, caches = chunked_prefill_forward(
                model, w, tokens, caches, offs, clens, act, maxlen,
                attention=attn_kernel, span=span,
            )
            caches = _constrain_all(caches)
            C = tokens.shape[1]
            at_end = (
                (p_lens - offs - 1)[:, None] == jnp.arange(C)[None, :]
            ).astype(logits.dtype)
            last_logits = jnp.einsum("bc,bcv->bv", at_end, logits)
            key, sub = jax.random.split(key)
            firsts = _sample_dynamic(
                last_logits, sub, new_temps, self.top_k, self.top_p
            )
            lengths = _vec(jnp.where(fin, p_lens, lengths))
            last = _vec(jnp.where(fin, firsts, last))
            temps = _vec(jnp.where(fin, new_temps, temps))
            return caches, lengths, last, temps, key, firsts

        def copy_prefix(caches, src_idx, copy_mask, copy_len):
            return _constrain_all(
                prefix_copy(caches, src_idx, copy_mask, copy_len, maxlen)
            )

        # -- paged programs (ISSUE 7): same sampling/advance math as
        # the fixed-arena decode/chunk bodies, with storage indirected
        # through the block tables. Compiled once per table-length
        # bucket (decode) / (chunk width, table bucket) pair — tables
        # ride as a traced [num_slots, T] argument, so only the bucket
        # SHAPE triggers a compile.
        def paged_decode(w, caches, tables, lengths, last, temps,
                         active, key):
            def body(i, carry):
                caches, lengths, last, key, toks = carry
                positions = jnp.minimum(lengths, maxlen - 1)
                logits, caches = paged_token_decode_step(
                    model, w, last, positions, caches, tables,
                    self.block_size, maxlen, active,
                    local=mesh is None, attention=attn_kernel,
                    kv_dtype=self.kv_dtype,
                )
                caches = _constrain_all(caches)
                key, sub = jax.random.split(key)
                sampled = _sample_dynamic(
                    logits, sub, temps, self.top_k, self.top_p
                )
                lengths = _vec(jnp.where(
                    active, jnp.minimum(lengths + 1, maxlen), lengths
                ))
                toks = toks.at[i].set(sampled)
                last = _vec(jnp.where(active, sampled, last))
                return caches, lengths, last, key, toks

            toks0 = jnp.zeros((k_window, self.num_slots), jnp.int32)
            caches, lengths, last, key, toks = jax.lax.fori_loop(
                0, k_window, body, (caches, lengths, last, key, toks0)
            )
            return caches, lengths, last, key, toks

        def paged_chunk_step(w, caches, tables, tokens, offs, clens,
                             act, fin, lengths, last, temps, p_lens,
                             new_temps, key):
            """The ONLY paged prefill program: cold prompts are chunks
            from offset 0, prefix hits start at their shared-block
            boundary — no whole-bucket prefill, no copy program (the
            splice already happened in the host block table)."""
            logits, caches = paged_chunk_forward(
                model, w, tokens, caches, tables, offs, clens, act,
                self.block_size, maxlen, local=mesh is None,
                attention=attn_kernel, kv_dtype=self.kv_dtype,
            )
            caches = _constrain_all(caches)
            C = tokens.shape[1]
            at_end = (
                (p_lens - offs - 1)[:, None] == jnp.arange(C)[None, :]
            ).astype(logits.dtype)
            last_logits = jnp.einsum("bc,bcv->bv", at_end, logits)
            key, sub = jax.random.split(key)
            firsts = _sample_dynamic(
                last_logits, sub, new_temps, self.top_k, self.top_p
            )
            lengths = _vec(jnp.where(fin, p_lens, lengths))
            last = _vec(jnp.where(fin, firsts, last))
            temps = _vec(jnp.where(fin, new_temps, temps))
            return caches, lengths, last, temps, key, firsts

        def offload_rows(caches, ids):
            # read-only: the pool is NOT donated — it survives for the
            # same step's admissions to write into
            return gather_blocks(caches, ids)

        def restore_rows(caches, ids, rows):
            return _constrain_all(scatter_blocks(caches, ids, rows))

        def resume_state(lengths, last, temps, mask, r_len, r_last,
                         r_temps):
            return (
                _vec(jnp.where(mask, r_len, lengths)),
                _vec(jnp.where(mask, r_last, last)),
                _vec(jnp.where(mask, r_temps, temps)),
            )

        # -- speculative verify (ISSUE 8): ONE batched forward scores a
        # whole draft window for every verifying slot — row j of the
        # [num_slots, K+1] sample matrix is the model's own token for
        # position offs+j+1, which the host compares against the drafts
        # (accept the longest matching prefix + one bonus token). The
        # window width is STATIC (spec_k + 1); per-slot shorter drafts
        # ride the same program via the n_fed mask — one verify compile
        # total on the fixed arena, one per table bucket paged. One key
        # split per round covers all window positions (temp>0 streams
        # therefore diverge from plain decode, like chunked prefill;
        # temp-0 rows are argmax and key-free).
        # The round's host-built vectors ride as ONE packed [num_slots,
        # W+3] int32 upload (tokens | offset | n_fed | active) — four
        # separate stage calls measurably taxed the round on
        # dispatch-bound backends, where per-transfer overhead rivals
        # the dispatch itself.
        W_spec = (self.spec_k + 1) if self.speculative else 0

        def _unpack_verify(packed):
            tokens = packed[:, :W_spec]
            offs = packed[:, W_spec]
            n_fed = packed[:, W_spec + 1]
            act = packed[:, W_spec + 2] != 0
            return tokens, offs, n_fed, act

        def _sample_window(logits, temps, key):
            B, C, V = logits.shape
            key, sub = jax.random.split(key)
            sampled = _sample_dynamic(
                logits.reshape(B * C, V), sub,
                jnp.repeat(temps, C), self.top_k, self.top_p,
            ).reshape(B, C)
            return key, sampled

        def spec_verify(w, caches, packed, temps, key, span=None):
            tokens, offs, n_fed, act = _unpack_verify(packed)
            logits, caches = verify_forward(
                model, w, tokens, caches, offs, n_fed, act, maxlen,
                attention=attn_kernel, span=span,
            )
            caches = _constrain_all(caches)
            key, sampled = _sample_window(logits, temps, key)
            return caches, key, sampled

        def paged_spec_verify(w, caches, tables, packed, temps, key):
            tokens, offs, n_fed, act = _unpack_verify(packed)
            logits, caches = paged_verify_forward(
                model, w, tokens, caches, tables, offs, n_fed, act,
                self.block_size, maxlen, local=mesh is None,
                attention=attn_kernel, kv_dtype=self.kv_dtype,
            )
            caches = _constrain_all(caches)
            key, sampled = _sample_window(logits, temps, key)
            return caches, key, sampled

        # -- completion scoring (ISSUE 19): verify-WITHOUT-accept. One
        # chunk/verify-shaped forward feeds prompt+completion[:-1] on
        # lane 0 of a caches pytree that is NOT donated and whose
        # updated copy is DISCARDED — the live arena never changes, so
        # scoring composes with in-flight serving. Paged mode scores
        # through a scratch arange block table (the one-hot writes land
        # in the discarded copy only); row j of the logits scores the
        # token at absolute position j+1, which is exactly the
        # completion logprob/greedy-token oracle the quant bench gates
        # consume. Compiled per (width bucket[, table bucket / span])
        # — the same closed ladders the serving programs use.
        def paged_score(w, caches, tables, tokens, clens, act, targets):
            offs = jnp.zeros((self.num_slots,), jnp.int32)
            logits, _ = paged_chunk_forward(
                model, w, tokens, caches, tables, offs, clens, act,
                self.block_size, maxlen, local=mesh is None,
                attention=attn_kernel, kv_dtype=self.kv_dtype,
            )
            row = logits[0]  # [C, vocab] — the scoring lane
            lp = jax.nn.log_softmax(row, axis=-1)
            tlp = jnp.take_along_axis(lp, targets[:, None], axis=-1)
            greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
            return tlp[:, 0], greedy

        def fixed_score(w, caches, tokens, clens, act, targets,
                        span=None):
            offs = jnp.zeros((self.num_slots,), jnp.int32)
            logits, _ = verify_forward(
                model, w, tokens, caches, offs, clens, act, maxlen,
                attention=attn_kernel, span=span,
            )
            row = logits[0]
            lp = jax.nn.log_softmax(row, axis=-1)
            tlp = jnp.take_along_axis(lp, targets[:, None], axis=-1)
            greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
            return tlp[:, 0], greedy

        # -- SP long-prompt prefill program (ISSUE 11): one whole-
        # prompt forward over the SP mesh returning logits AND every
        # layer's K/V rows, landed straight into the block pool via
        # the same scatter program resume uses, plus the first-token
        # sample — ONE dispatch per long prompt. Compiled per (padded
        # length, table bucket) pair, both closed ladders.
        if self.sp_mesh is not None:
            from elephas_tpu.serving.sp_prefill import sp_prefill_forward

            sp_mesh_, sp_ax_, sp_mech_ = (
                self.sp_mesh, self.sp_axis, self.sp_mechanism
            )

            def sp_step(w, tokens, p_idx):
                """Mesh half of the SP prefill: the sharded forward
                only. K/V rows and the prompt-end logits row hop back
                to the default device on the host side; sampling and
                the block landing run UNMESHED (the scatter program
                resume already owns) — nothing mesh-committed ever
                touches the pool or the key stream, so decode stays
                unmeshed ("proceeds unmeshed" is the contract) and no
                downstream program recompiles."""
                logits, kv = sp_prefill_forward(
                    model, w, tokens, sp_mesh_, sp_ax_, sp_mech_,
                    maxlen,
                )
                row = jax.lax.dynamic_index_in_dim(
                    logits[0], p_idx - 1, axis=0, keepdims=False
                )
                return kv, row

            def sp_sample(row, temp, key):
                key, sub = jax.random.split(key)
                tok = _sample_dynamic(
                    row[None], sub, temp, self.top_k, self.top_p
                )[0]
                return tok, key

            self._sp_jit = jax.jit(sp_step)
            self._sp_sample_jit = jax.jit(sp_sample)
        else:
            self._sp_jit = None
            self._sp_sample_jit = None
        # SP weight staging (mesh-replicated) built lazily on the
        # first long prompt; refresh_weights() drops it
        self._sp_weights = None

        # the fixed program set: ONE decode window + one prefill per
        # prompt bucket (p_lens/admit/new_temps ride as traced vectors,
        # so only the bucket SHAPE triggers a compile), plus ONE prefix
        # copy shape and one chunk program per chunk width (a single
        # width under `prefill_chunk`, suffix buckets otherwise).
        # Paged mode compiles its OWN closed set instead: one decode
        # per table bucket, one chunk per (width, table bucket), one
        # gather/scatter per table bucket (preempt/resume), one
        # resume-state select.
        self._init_jit = jax.jit(init_state)
        if self.paged:
            self._paged_decode_jit = jax.jit(
                paged_decode, donate_argnums=(1, 3, 4, 7)
            )  # args: w, caches, tables, lengths, last, temps,
            #         active, key
            self._paged_chunk_jit = jax.jit(
                paged_chunk_step, donate_argnums=(1, 8, 9, 10, 13)
            )  # args: w, caches, tables, tokens, offs, clens, act,
            #         fin, lengths, last, temps, p_lens, new_temps, key
            self._gather_jit = jax.jit(offload_rows)
            self._scatter_jit = jax.jit(
                restore_rows, donate_argnums=(0,)
            )
            self._resume_state_jit = jax.jit(
                resume_state, donate_argnums=(0, 1, 2)
            )
            self._verify_jit = (
                jax.jit(paged_spec_verify, donate_argnums=(1, 5))
                if self.speculative else None
            )  # args: w, caches, tables, packed, temps, key
            self._score_jit = jax.jit(paged_score)
            # args: w, caches, tables, tokens, clens, act, targets —
            # NOTHING donated: the updated caches are discarded, the
            # live arena survives untouched
        else:
            self._prefill_jit = jax.jit(
                prefill, donate_argnums=(1, 2, 3, 4, 9)
            )  # args: w, caches, lengths, last, temps, rows, p_lens,
            #         admit, new_temps, key
            self._decode_jit = jax.jit(
                decode, donate_argnums=(1, 2, 3, 6),
                static_argnums=(7,),
            )  # trailing STATIC span (flash block-span reads): one
            #   compile per touched span bucket — naive always passes
            #   None, keeping the seed's single decode program
            self._chunk_jit = jax.jit(
                chunk_step, donate_argnums=(1, 2, 3, 4, 15),
                static_argnums=(16, 17),
            )  # args: w, caches, lengths, last, temps, tokens, offs,
            #         clens, act, fin, p_lens, new_temps, src_idx,
            #         copy_mask, copy_len, key, has_copy (static),
            #         span (static)
            self._copy_jit = jax.jit(copy_prefix, donate_argnums=(0,))
            self._verify_jit = (
                jax.jit(
                    spec_verify, donate_argnums=(1, 4),
                    static_argnums=(5,),
                )
                if self.speculative else None
            )  # args: w, caches, packed, temps, key, span (static)
            self._score_jit = jax.jit(
                fixed_score, static_argnums=(6,)
            )  # args: w, caches, tokens, clens, act, targets, span
            #   (static) — nothing donated, updated caches discarded

        self.refresh_weights()
        self._caches, self._lengths, self._last, self._temps = (
            self._init_jit()
        )
        self._key = self._stage(
            np.asarray(jax.random.PRNGKey(self._seed))
        )
        # decode-active mask: host mirror + staged device copy,
        # re-uploaded only when membership changes (admission finalize /
        # reclaim), not every window
        self._active_host = np.zeros((self.num_slots,), bool)
        self._active_dev = self._stage_slots(self._active_host.copy())
        self._active_dirty = False
        # paged staging: device block tables rebuilt only when the
        # scheduler's tables change or the bucket shifts, plus the
        # host store of offloaded (preempted) requests' K/V
        self._tables_cache: tuple | None = None
        self._offloaded: dict[int, _OffloadRecord] = {}
        # speculative host state (ISSUE 8): the drafter, the per-request
        # acceptance throttle, and the device-state dirty flag — verify
        # rounds track positions from HOST truth (resident length =
        # prompt + generated - 1), leaving the device length/last
        # vectors stale; the flag triggers a re-stage before any plain
        # decode window reads them (the all-throttled fallback path)
        self._drafter = (
            resolve_drafter(
                spec_drafter, num_slots=self.num_slots,
                maxlen=self.maxlen, vocab=self.vocab,
            ) if self.speculative else None
        )
        self._spec_throttle = (
            AcceptanceThrottle() if self.speculative else None
        )
        self._spec_dirty = False
        # HTTP/SSE front door (ISSUE 10): attached by
        # ``SparkModel.serve(gateway_port=...)`` (or any host that
        # builds a serving.gateway.Gateway around this engine); the
        # engine's context-manager exit stops it and severs live SSE
        # connections, so ``with model.serve(gateway_port=...) as eng:``
        # can never leak a bound port or a zombie keep-alive handler
        self.gateway = None

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        """Stop the attached gateway (if any): sever live SSE
        connections, release the port, join its threads. Idempotent;
        the engine itself stays usable in-process afterwards."""
        gw = self.gateway
        if gw is not None:
            self.gateway = None
            gw.stop()

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # -- device staging ------------------------------------------------

    def _stage(self, arr):
        """Host value → device, replicated under the mesh (gang-safe)."""
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import put_global

        return put_global(np.asarray(arr), NamedSharding(self.mesh, P()))

    def _host(self, leaf) -> np.ndarray:
        if self.mesh is None:
            return np.asarray(leaf)
        from elephas_tpu.parallel.mesh import host_read

        return host_read(leaf, self.mesh)

    def refresh_weights(self, version: int | None = None) -> None:
        """(Re-)upload the model's weights — call after further
        training; the compiled programs take them as arguments, so no
        recompile happens. ``version`` stamps the new weight
        generation (ISSUE 20 deploy subscriber); ``None`` keeps the
        current stamp (ad-hoc in-place refresh, pre-versioned callers).

        Flushes the prefix cache: resident donor K/V was computed
        under the OLD weights, and a donor copy would silently splice
        stale rows into a new-weights request — breaking the engine's
        token-exactness contract with no error. (In-flight requests
        keep their slots and finish on mixed weights, the same
        documented behavior as refreshing mid-decode.)"""
        import jax.numpy as jnp

        if version is not None:
            self.weight_version = int(version)
        elif not hasattr(self, "weight_version"):
            # constructor's first call, before any attribute setup
            self.weight_version = 0
        # lifecycle event (ISSUE 13): a weight push travelling
        # worker → PS → engine ends HERE — emitting under the caller's
        # trace scope stamps the same trace id the push carried, so
        # the deployment is one causal story on the merged timeline.
        # getattr-guarded: the constructor calls refresh_weights()
        # before the telemetry capture exists.
        tracer = getattr(self, "_tracer", None)
        if tracer is not None:
            tracer.emit(
                "serve.refresh_weights", engine=self.telemetry_label,
                weight_version=self.weight_version,
            )
        gauge = getattr(self, "_g_weight_version", None)
        if gauge is not None:
            gauge.set(self.weight_version)
        # guarded for the constructor's first call (scheduler not
        # built yet — nothing cached before weights exist)
        scheduler = getattr(self, "scheduler", None)
        if scheduler is not None:
            scheduler.flush_prefix_cache()
            # slots mid-chunked-prefill hold rows partially computed
            # under the OLD weights: when they finalize they must NOT
            # re-register as donors, or the stale-splice the flush
            # prevents comes back through the side door
            self._stale_prefill = set(self._prefilling)
        # a draft-model drafter re-uploads ITS model's weights and
        # drops its committed frontiers (full re-ingest): the draft
        # model may have been retrained alongside the target — stale
        # draft weights would silently collapse acceptance and turn
        # speculation off through the throttle with no signal
        drafter = getattr(self, "_drafter", None)
        if drafter is not None:
            drafter.refresh_weights()
            # the draft model now serves the SAME generation as the
            # target — without the stamp a mixed-version fleet debug
            # view would show the drafter forever at generation 0
            drafter.weight_version = self.weight_version
        # SP prefill keeps its own mesh-replicated weight staging —
        # drop it so the next long prompt re-stages the new weights
        self._sp_weights = None

        if self.mesh is None:
            self._weights = {
                v.path: jnp.asarray(v.value) for v in self.model.variables
            }
            return
        from elephas_tpu.models.transformer import _decode_shardings
        from elephas_tpu.parallel.mesh import put_global

        var_sh = _decode_shardings(
            list(self.model.variables), self.mesh, self.model_axis,
            self._rules,
        )
        self._weights = {
            v.path: put_global(np.asarray(v.value), s)
            for v, s in zip(self.model.variables, var_sh)
        }

    # -- request API ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, eos_id: int | None = None,
               on_token=None, priority: int = 0,
               tenant: str | None = None,
               ttft_deadline_ms: float | None = None) -> Request:
        """Queue one generation request (admitted at the next step —
        submission is legal at any time, including mid-flight). Every
        gang process must submit the identical sequence of requests.
        ``on_token(token, done)`` streams tokens to the caller as they
        land; a raising callback fails only ITS request (``req.error``
        set, KV slot reclaimed) — the engine keeps serving.
        ``priority`` matters only with ``preemption=True``: an arrival
        may swap out active requests of strictly lower priority when
        the block pool is exhausted.

        Paged mode: a request whose prompt + budget can NEVER fit the
        block pool is rejected loudly but GRACEFULLY — ``req.error``
        set, ``req.done`` True, never queued — instead of raising or
        (worse) wedging the queue head forever at admission.

        SLO scheduling (ISSUE 10): ``tenant`` accounts the request
        under a policy-declared tenant (fair share, per-tenant stats);
        ``ttft_deadline_ms`` declares its time-to-first-token budget
        (deadline-EDF ordering + SLO attainment counters). Both are
        validated LOUDLY: an unknown tenant, a non-positive deadline,
        or a deadline on an engine whose policy does not read
        deadlines raises ValueError — silently recording either would
        let the caller believe in isolation/urgency the scheduler
        never delivers. A policy with admission control may refuse the
        submit outright: like the paged never-fit case the request
        comes back ``done`` with ``req.error`` set to
        :class:`~elephas_tpu.serving.policy.AdmissionRejected`
        (carrying the Retry-After hint the gateway serves as a 429)."""
        prompt = np.asarray(prompt).reshape(-1)
        p = len(prompt)
        if p < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} < 1")
        if p + max_new_tokens > self.maxlen:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model's maxlen ({self.maxlen})"
            )
        if temperature < 0:
            raise ValueError(f"temperature={temperature} < 0")
        # fail HERE, not mid-flight in the prefill wave (where the
        # request would already hold a leased slot): a custom bucket
        # ladder may top out below the model's maxlen. Chunked prefill
        # never pads to a prompt bucket, so the ladder doesn't bound it.
        if not self.prefill_chunk:
            self.scheduler.bucket_for(p)
        if priority and not self.preemption:
            # ISSUE 8 satellite (knob-validation parity with the paged
            # knobs): only the preemption path ever consults priority —
            # a caller passing it on any other engine is expressing an
            # expectation this engine cannot honor, and silence here
            # would let them believe their high-priority traffic jumps
            # the queue. Warn (not raise): the request itself is valid.
            logger.warning(
                "submit(priority=%d) on an engine without "
                "preemption=True — priority is recorded but IGNORED "
                "(admission stays FIFO); serve with paged=True, "
                "preemption=True for priority scheduling", priority,
            )
        # SLO knob validation (ISSUE 10 satellite) — loud, per the
        # docstring's contract
        if tenant is not None:
            if self.policy is None:
                raise ValueError(
                    f"submit(tenant={tenant!r}) on an engine without a "
                    f"policy — serve with policy=/tenants= to declare "
                    f"tenants before accounting requests under them"
                )
            if not self.policy.knows(tenant):
                raise ValueError(
                    f"unknown tenant {tenant!r} — declared tenants: "
                    f"{sorted(self.policy.tenants) or '[none]'} (plus "
                    f"the implicit {DEFAULT_TENANT!r})"
                )
        if ttft_deadline_ms is not None:
            if not float(ttft_deadline_ms) > 0:
                raise ValueError(
                    f"ttft_deadline_ms={ttft_deadline_ms} must be "
                    f"positive — a deadline at or before submit time "
                    f"can never be met"
                )
            if self.policy is None or not self.policy.reads_deadlines:
                raise ValueError(
                    "submit(ttft_deadline_ms=) needs a deadline-aware "
                    "policy (e.g. FairSharePolicy) — this engine's "
                    "policy never reads deadlines, so the knob would "
                    "be a silent no-op"
                )
        req = self.scheduler.make_request(
            prompt, max_new_tokens, temperature=temperature, eos_id=eos_id,
            on_token=on_token, priority=priority, tenant=tenant,
            ttft_deadline_ms=ttft_deadline_ms,
        )
        req.submit_time = time.perf_counter()
        # trace context minted HERE (ISSUE 12): the rid is the trace
        # identity for every lifecycle event/record downstream (the
        # gateway echoes it back as X-Request-Id and in the SSE/JSON
        # envelopes)
        req.submit_step = self.scheduler._steps
        req.exemplar = {"rid": str(req.rid)}
        rec = self._fr_new(req)
        submit_seq = self._tracer.emit(
            "serve.submit", rid=req.rid,
            tenant=DEFAULT_TENANT if tenant is None else str(tenant),
            prompt_tokens=p, max_new_tokens=int(max_new_tokens),
            step=req.submit_step,
        )
        if rec is not None:
            rec["submit_seq"] = submit_seq
        if self.paged:
            need = blocks_for(p + max_new_tokens, self.block_size)
            if need > self.num_blocks:
                # ISSUE 7 satellite: this request could sit at the
                # queue head forever (admission can never free enough
                # blocks) — reject it now, loudly, without poisoning
                # the engine for everyone behind it
                req.error = RuntimeError(
                    f"request {req.rid} needs {need} KV blocks "
                    f"(prompt {p} + max_new_tokens {max_new_tokens} "
                    f"at block_size {self.block_size}) but the pool "
                    f"only has {self.num_blocks} — it can never be "
                    f"admitted; rejected at submit"
                )
                req.done = True
                self._m_rejected.inc()
                self._tenant_child(self._mf_tenant_rejected, tenant).inc()
                logger.warning("%s", req.error)
                self._fr_finish(req, "rejected_capacity")
                self.finished[req.rid] = req
                self._evict_finished()
                return req
        if self.policy is not None:
            # overload admission control (ISSUE 10): the policy sees
            # the queue's outstanding token debt and may shed THIS
            # request now — loudly, with a deterministic Retry-After —
            # instead of letting it time out at the back of a queue
            # that can only grow
            tenant_debt = self.scheduler.queued_tokens_for(tenant)
            verdict = self.policy.admission_verdict(
                req, self.scheduler.queued_tokens, tenant_debt,
            )
            # verdict event + record (ISSUE 12): the fairness state
            # the decision was made against rides along, so a trace
            # answers "queued behind whose debt?" without replaying
            # the policy
            self._tracer.emit(
                "serve.admission_verdict", rid=req.rid,
                admitted=verdict.admitted, reason=verdict.reason,
                queued_tokens=self.scheduler.queued_tokens,
                tenant_queued_tokens=tenant_debt,
            )
            if rec is not None:
                rec["verdict"] = {
                    "admitted": verdict.admitted,
                    "reason": verdict.reason,
                    "retry_after_s": verdict.retry_after_s,
                    "queued_tokens": self.scheduler.queued_tokens,
                    "tenant_queued_tokens": tenant_debt,
                    "virtual_counters": self.policy.snapshot_counters(),
                }
            if not verdict.admitted:
                req.error = AdmissionRejected(
                    f"request {req.rid} rejected by "
                    f"{type(self.policy).__name__}: {verdict.reason}; "
                    f"retry after {verdict.retry_after_s:.1f}s",
                    retry_after_s=verdict.retry_after_s,
                )
                req.done = True
                self._m_admission_rejected.inc()
                self._tenant_child(self._mf_tenant_rejected, tenant).inc()
                logger.warning("%s", req.error)
                self._fr_finish(req, "rejected_admission")
                self.finished[req.rid] = req
                self._evict_finished()
                return req
        self.scheduler.submit(req)
        return req

    def _tenant_child(self, family, tenant):
        """The tenant-labeled child of ``family`` for this engine."""
        label = DEFAULT_TENANT if tenant is None else str(tenant)
        return family.labels(engine=self.telemetry_label, tenant=label)

    # -- request-scoped tracing (ISSUE 12) ------------------------------

    def _dispatch(self, program: str, fn, *args):
        """Run one compiled-program dispatch; when the call grew the
        program's jit cache (a compile happened inside it) record a
        named ``jit.compile`` span covering the dispatch, so
        mid-serve recompiles land on the same timeline as the request
        lifecycle events. Watch-free (one function call) under null
        mode; report-only always — nothing reads the cache size to
        make a decision."""
        if not self._trace_compiles:
            return fn(*args)
        try:
            before = int(fn._cache_size())
        except Exception:  # jax-version drift: dispatch unwatched
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        try:
            grew = int(fn._cache_size()) > before
        except Exception:  # jax-version drift mid-flight
            grew = False
        if grew:
            self._tracer.complete(
                "jit.compile", time.perf_counter() - t0,
                program=program, engine=self.telemetry_label,
            )
        return out

    def _fr(self, rid: int) -> dict | None:
        """The request's lifecycle record — in-flight first, then the
        finished ring (late entries like the spec round that ended the
        request append there). None when recording is off or the
        record was evicted."""
        if self._flight is None:
            return None
        rec = self._flight_live.get(rid)
        if rec is None:
            rec = self._flight.get(rid)
        return rec

    def _fr_new(self, req: Request) -> dict | None:
        """Open one in-flight lifecycle record at submit."""
        if self._flight is None:
            return None
        rec = {
            "rid": req.rid,
            "tenant": req.tenant,
            "prompt_tokens": len(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature,
            "priority": req.priority,
            "ttft_deadline_ms": req.ttft_deadline_ms,
            "submit_step": req.submit_step,
            "submit_seq": -1,  # set from the serve.submit instant
            # generation at submit: a mixed-version fleet is diagnosed
            # from traces — a request whose record says N running on a
            # replica that reports N+1 straddled a deployment
            "weight_version": self.weight_version,
            "verdict": None,
            # first-admission mirrors (the fields explain() names);
            # `admissions` keeps every entry (resume re-admissions)
            "admission_kind": None,
            "reuse_len": 0,
            "queue_wait_steps": None,
            "admissions": [],
            "chunks": [],
            "sp_prefill": None,
            "preemptions": [],
            "resumes": [],
            "spec_rounds": [],
            "first_token": None,
            "token_steps": [],
            "tokens": 0,
            "spec_drafted": 0,
            "spec_accepted": 0,
            "finish": None,
        }
        # every key is pre-seeded HERE and only ever re-assigned, so a
        # lock-free reader (explain() without the engine lock) always
        # sees a fixed-shape dict — deepcopy can never catch the dict
        # growing mid-iteration
        self._flight_live[req.rid] = rec
        return rec

    def _fr_finish(self, req: Request, reason: str) -> None:
        """Close the request's record and file it in the bounded ring;
        also emits the ``serve.finish`` lifecycle instant."""
        seq = self._tracer.emit(
            "serve.finish", rid=req.rid, reason=reason,
            tokens=len(req.tokens), step=self.scheduler._steps,
        )
        if self._flight is None:
            return
        rec = self._flight_live.get(req.rid)
        if rec is None:
            return
        rec["finish"] = {
            "reason": reason,
            "step": self.scheduler._steps,
            "seq": seq,
            "error": None if req.error is None else str(req.error),
        }
        rec["tokens"] = len(req.tokens)
        rec["spec_drafted"] = req.spec_drafted
        rec["spec_accepted"] = req.spec_accepted
        # file into the ring BEFORE dropping the live entry: a
        # lock-free explain() between the two stores must find the
        # record in at least one of them (never a spurious KeyError
        # for a request that exists)
        self._flight.record(req.rid, rec)
        self._flight_live.pop(req.rid, None)

    def _trace_admissions(self, plan) -> None:
        """One ``serve.admit`` instant + record entry per admission in
        the wave: kind (cold / prefix_hit / resume), slot, reuse
        length, and the queue wait in scheduler STEPS (logical — every
        gang process reconstructs the identical number)."""
        step = self.scheduler._steps
        for a in plan:
            if a.resume is not None:
                kind, reuse = "resume", 0
            elif a.donor_slot is not None or a.shared_len:
                kind, reuse = "prefix_hit", (a.reuse_len or a.shared_len)
            else:
                kind, reuse = "cold", 0
            req = a.req
            wait = (
                step - req.submit_step
                if req.submit_step is not None else None
            )
            seq = self._tracer.emit(
                "serve.admit", rid=req.rid, kind=kind, slot=a.slot,
                reuse_len=reuse, step=step, queue_wait_steps=wait,
            )
            rec = self._fr(req.rid)
            if rec is not None:
                rec["admissions"].append({
                    "kind": kind, "slot": a.slot, "reuse_len": reuse,
                    "step": step, "seq": seq,
                })
                if rec["admission_kind"] is None:
                    rec["admission_kind"] = kind
                    rec["reuse_len"] = reuse
                    rec["queue_wait_steps"] = wait

    def _emit(self, req: Request, token: int) -> bool:
        """Record one generated token; reclaim + file the request when
        it finished. Returns done.

        A raising per-token callback fails the request CLEANLY: before
        this guard, the exception unwound through step() after the
        scheduler had recorded the token but before reclaim, leaking
        the KV slot for the engine's lifetime."""
        self._m_tokens.inc()
        slot = req.slot
        now = time.perf_counter()
        req.token_times.append(now)
        rec = self._fr(req.rid) if self._flight is not None else None
        if rec is not None:
            rec["token_steps"].append(self.scheduler._steps)
        # latency histograms feed straight off the per-request arrival
        # times stats() already reports — one recording site, no drift.
        # Observations carry the rid as an exemplar (ISSUE 12): the
        # OpenMetrics scrape links a p99 bucket straight to the trace
        # of the request that landed in it.
        if len(req.token_times) == 1:
            seq = self._tracer.emit(
                "serve.first_token", rid=req.rid,
                step=self.scheduler._steps,
            )
            if req.submit_time is not None:
                ttft = now - req.submit_time
                self._m_ttft.observe(ttft, exemplar=req.exemplar)
                if rec is not None:
                    rec["first_token"] = {
                        "step": self.scheduler._steps, "seq": seq,
                        # wall-derived, EXPORT-ONLY (like every wall
                        # field in the telemetry layer)
                        "ttft_s": ttft,
                    }
                if req.ttft_deadline_ms is not None:
                    # SLO attainment (ISSUE 10): wall-clock TTFT meets
                    # the declared budget HERE and only here — report-
                    # only, never an input to the schedule
                    met = ttft * 1e3 <= req.ttft_deadline_ms
                    self._tenant_child(
                        self._mf_slo_met if met else self._mf_slo_missed,
                        req.tenant,
                    ).inc()
        else:
            self._m_itl.observe(
                now - req.token_times[-2], exemplar=req.exemplar
            )
        if self.policy is not None:
            self.policy.on_token(req)
            self._tenant_child(self._mf_tenant_tokens, req.tenant).inc()
        done = self.scheduler.on_token(slot, token)
        if req.on_token is not None:
            try:
                req.on_token(token, done)
            except Exception as e:
                req.error = e
                req.done = True
                done = True
                logger.warning(
                    "request %d failed in its on_token callback (%r) — "
                    "slot %d reclaimed, engine continues", req.rid, e, slot,
                )
        if done:
            req.finish_time = req.token_times[-1]
            self.scheduler.reclaim(slot)
            self._set_active(slot, False)
            self._m_finished.inc()
            if self.policy is not None:
                self.policy.on_finish(req)
            if self._spec_throttle is not None:
                self._spec_throttle.forget(req.rid)
            if req.error is not None:
                reason = "callback_error"
            elif (
                req.eos_id is not None and req.tokens
                and req.tokens[-1] == req.eos_id
            ):
                reason = "eos"
            else:
                reason = "budget"
            self._fr_finish(req, reason)
            self.finished[req.rid] = req
            self._evict_finished()
        return done

    def _evict_finished(self) -> None:
        """Trim the bounded finished-request registry — LOUDLY but
        RATE-LIMITED (ISSUE 5 satellite): the registry-backed
        ``finished_evicted`` counter keeps EVERY increment for stats
        and scrapes, while the warning fires only on the first eviction
        and every 1024th after — a hot loop evicting per token cannot
        turn the log into the bottleneck. The warning cadence runs on a
        PLAIN count (telemetry never drives control flow — under null
        mode the registry counter reads 0 forever, which would make
        ``0 % 1024 == 0`` fire the warning on EVERY eviction). Requests
        an in-flight :meth:`run` call has yet to return are never
        evicted (the registry may temporarily exceed its bound
        instead)."""
        while len(self.finished) > self._finished_bound:
            if len(self.finished) - len(self._protected) <= 0:
                return  # only protected residents over the bound — a
                # full scan would find no victim (hot path: this runs
                # per token completion during a large run())
            victim = next(
                (rid for rid in self.finished
                 if rid not in self._protected),
                None,
            )
            if victim is None:
                return  # every resident request is protected
            self.finished.pop(victim)
            self._m_finished_evicted.inc()
            self._tracer.emit("serve.evict", rid=victim)
            self._evictions_seen += 1
            evicted = self._evictions_seen
            if evicted == 1 or evicted % 1024 == 0:
                logger.warning(
                    "finished-request registry hit its bound (%d): "
                    "evicted request %d (%d evicted so far) — consume "
                    "results promptly or keep your own Request handles "
                    "from submit()",
                    self._finished_bound, victim, evicted,
                )

    def _fixed_span(self, max_pos_excl: int):
        """Static attended-span bucket for the fixed arena's flash
        programs: the smallest span bucket covering ``max_pos_excl``
        resident positions. ``None`` in naive mode (the seed
        full-``maxlen`` program) and for the paged arena (its span is
        the table bucket already)."""
        if self.attention != "flash" or self.paged:
            return None
        n = max(1, min(self.maxlen, int(max_pos_excl)))
        return span_bucket_for(n, self._sbuckets)

    def _decode_span(self):
        """Span bucket for one decode window: every decoding slot's
        resident length plus the window's worth of new positions."""
        m = 0
        for slot, req in self.scheduler.active.items():
            if slot in self._prefilling:
                continue
            m = max(m, len(req.prompt) + len(req.tokens) - 1)
        return self._fixed_span(m + self.steps_per_sync)

    def _set_active(self, slot: int, value: bool) -> None:
        if bool(self._active_host[slot]) != value:
            self._active_host[slot] = value
            self._active_dirty = True

    def _sync_active(self):
        if self._active_dirty:
            self._active_dev = self._stage_slots(self._active_host.copy())
            self._active_dirty = False
        return self._active_dev

    def _stage_slots(self, arr):
        """Host ``[num_slots, ...]`` value → device, slot axis over the
        batch axes (gang-safe)."""
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import put_global

        spec = (self.batch_axes,) + (None,) * (np.ndim(arr) - 1)
        return put_global(
            np.asarray(arr), NamedSharding(self.mesh, P(*spec))
        )

    def _prefill_wave(self, admitted: list[Request]) -> None:
        """Prefill one admission wave: ONE program launch per prompt
        bucket covers every request of that bucket in the wave."""
        with self._tracer.span("serve.prefill_wave", reqs=len(admitted)):
            self._prefill_wave_inner(admitted)

    def _prefill_wave_inner(self, admitted: list[Request]) -> None:
        by_bucket: dict[int, list[Request]] = {}
        for req in admitted:
            b = self.scheduler.bucket_for(len(req.prompt))
            by_bucket.setdefault(b, []).append(req)
        for bucket in sorted(by_bucket):
            reqs = by_bucket[bucket]
            rows = np.zeros((self.num_slots, bucket), np.int32)
            p_lens = np.ones((self.num_slots,), np.int32)
            admit = np.zeros((self.num_slots,), bool)
            new_temps = np.zeros((self.num_slots,), np.float32)
            for req in reqs:
                rows[req.slot, : len(req.prompt)] = req.prompt
                p_lens[req.slot] = len(req.prompt)
                admit[req.slot] = True
                new_temps[req.slot] = req.temperature
            (self._caches, self._lengths, self._last, self._temps,
             self._key, firsts) = self._dispatch(
                "prefill", self._prefill_jit,
                self._weights, self._caches, self._lengths, self._last,
                self._temps, self._stage_slots(rows),
                self._stage_slots(p_lens), self._stage_slots(admit),
                self._stage_slots(new_temps), self._key,
            )
            toks = self._host(firsts)
            for req in reqs:
                # prompt rows are resident from here: index them before
                # _emit (a 1-token request reclaims inside _emit, and
                # reclaim only retains slots the cache already knows)
                self.scheduler.on_prefill_complete(req)
                self._set_active(req.slot, True)
                self._note_prefill(req, bucket)
                seq = self._tracer.emit(
                    "serve.prefill", rid=req.rid, bucket=bucket,
                    prompt_tokens=len(req.prompt),
                    step=self.scheduler._steps,
                )
                rec = self._fr(req.rid)
                if rec is not None:
                    # whole-prompt wave: one "chunk" covering it all,
                    # so explain()'s chunk list is the prefill story
                    # on every arena/config
                    rec["chunks"].append({
                        "offset": 0, "take": len(req.prompt),
                        "step": self.scheduler._steps, "seq": seq,
                    })
                self._emit(req, int(toks[req.slot]))

    def _copy_vectors(self, copies):
        """``(src_idx, copy_mask, copy_len)`` staging vectors for a
        wave's donor transplants — shared by the fused (in-chunk) and
        standalone copy program calls so their semantics cannot
        diverge."""
        src = np.zeros((self.num_slots,), np.int32)
        mask = np.zeros((self.num_slots,), bool)
        clen = np.zeros((self.num_slots,), np.int32)
        for a in copies:
            src[a.slot] = a.donor_slot
            mask[a.slot] = True
            clen[a.slot] = a.reuse_len
        return src, mask, clen

    def _run_chunk(self, items: list, width: int, copies=()):
        """One chunk-program call: each ``(admission, progress, take)``
        item advances ``take`` prompt tokens (``<= width``) of its
        slot's prompt from absolute offset ``progress``. Items whose
        prompt completes sample their first token and join decode.
        ``copies`` — admissions whose donor transplant rides fused
        inside this same call (their suffix items must be present too).
        Returns ``(request, token, done)`` emissions of finalized
        requests."""
        with self._tracer.span(
            "serve.chunk", width=width, slots=len(items),
            copies=len(copies),
        ):
            return self._run_chunk_inner(items, width, copies)

    def _run_chunk_inner(self, items: list, width: int, copies=()):
        rows = np.zeros((self.num_slots, width), np.int32)
        offs = np.zeros((self.num_slots,), np.int32)
        clens = np.zeros((self.num_slots,), np.int32)
        act = np.zeros((self.num_slots,), bool)
        fin = np.zeros((self.num_slots,), bool)
        p_lens = np.zeros((self.num_slots,), np.int32)
        new_temps = np.zeros((self.num_slots,), np.float32)
        src, cmask, clen = self._copy_vectors(copies)
        finalized = []
        for adm, progress, take in items:
            req, slot = adm.req, adm.slot
            rows[slot, :take] = req.prompt[progress:progress + take]
            offs[slot] = progress
            clens[slot] = take
            act[slot] = True
            done_prefill = progress + take == len(req.prompt)
            fin[slot] = done_prefill
            p_lens[slot] = len(req.prompt)
            new_temps[slot] = req.temperature
            if done_prefill:
                finalized.append(adm)
            seq = self._tracer.emit(
                "serve.prefill_chunk", rid=req.rid, offset=progress,
                take=take, final=done_prefill,
                step=self.scheduler._steps,
            )
            crec = self._fr(req.rid)
            if crec is not None:
                crec["chunks"].append({
                    "offset": progress, "take": take,
                    "step": self.scheduler._steps, "seq": seq,
                })
        if self.paged:
            # paged chunk: the block tables carry the storage mapping
            # (incl. any spliced prefix blocks) — no copy vectors
            (self._caches, self._lengths, self._last, self._temps,
             self._key, firsts) = self._dispatch(
                "paged_chunk", self._paged_chunk_jit,
                self._weights, self._caches, self._staged_tables(),
                self._stage_slots(rows), self._stage_slots(offs),
                self._stage_slots(clens), self._stage_slots(act),
                self._stage_slots(fin), self._lengths, self._last,
                self._temps, self._stage_slots(p_lens),
                self._stage_slots(new_temps), self._key,
            )
        else:
            # flash block-span read: the attended row slice need only
            # cover this call's deepest written position (queries see
            # the prefix copy + earlier chunks, all below it)
            span = self._fixed_span(
                max(progress + take for _a, progress, take in items)
            ) if items else None
            (self._caches, self._lengths, self._last, self._temps,
             self._key, firsts) = self._dispatch(
                "chunk_prefill", self._chunk_jit,
                self._weights, self._caches, self._lengths, self._last,
                self._temps, self._stage_slots(rows),
                self._stage_slots(offs), self._stage_slots(clens),
                self._stage_slots(act), self._stage_slots(fin),
                self._stage_slots(p_lens), self._stage_slots(new_temps),
                self._stage_slots(src), self._stage_slots(cmask),
                self._stage_slots(clen), self._key, bool(copies), span,
            )
        emitted = []
        if finalized:
            toks = self._host(firsts)
            for adm in finalized:
                req = adm.req
                self._prefilling.pop(adm.slot, None)
                if adm.slot in self._stale_prefill:
                    # prefill straddled refresh_weights(): rows mix
                    # weight generations — decode fine, donate never
                    self._stale_prefill.discard(adm.slot)
                else:
                    self.scheduler.on_prefill_complete(req)
                self._set_active(adm.slot, True)
                self._note_prefill(
                    req, self.scheduler.bucket_for(len(req.prompt))
                )
                self._emit(req, int(toks[adm.slot]))
                emitted.append((req, req.tokens[-1], req.done))
        return emitted

    # -- paged execution (ISSUE 7) -------------------------------------

    def _staged_tables(self):
        """Device copy of the scheduler's block tables, ``[num_slots,
        T]`` for the bucketed ``T`` covering the longest live table —
        rebuilt only when tables mutate or the bucket shifts. Rows pad
        with the sentinel id ``num_blocks`` (matches no pool row);
        idle slots are all-sentinel."""
        sched = self.scheduler
        need = max(
            (len(t) for t in sched.tables.values()), default=1
        )
        T = table_bucket_for(need, self._tbuckets)
        key = (sched.tables_version, T)
        if self._tables_cache is None or self._tables_cache[0] != key:
            arr = np.full((self.num_slots, T), self.num_blocks, np.int32)
            for slot, table in sched.tables.items():
                arr[slot, : len(table)] = table
            self._tables_cache = (key, self._stage_slots(arr))
        return self._tables_cache[1]

    def _pad_ids(self, blocks):
        """Block ids padded to their table bucket with the sentinel —
        gather/scatter programs compile once per bucket, not per
        count."""
        Tb = table_bucket_for(max(1, len(blocks)), self._tbuckets)
        ids = np.full((Tb,), self.num_blocks, np.int32)
        ids[: len(blocks)] = blocks
        return ids

    def _offload(self, pre) -> None:
        """Swap a preemption victim's K/V blocks to host memory. MUST
        run before any pool-writing program of the same step: the
        scheduler already re-leased the blocks on paper, but the device
        rows stay intact until the next write, and the gather is
        dispatched against the CURRENT pool value (the jit data
        dependency keeps it ordered before any donating consumer)."""
        req = pre.req
        with self._tracer.span(
            "serve.preempt", rid=req.rid, blocks=len(pre.blocks),
        ) as sp:
            rec = self._fr(req.rid)
            if rec is not None:
                rec["preemptions"].append({
                    "blocks": len(pre.blocks), "cur_len": pre.cur_len,
                    "step": self.scheduler._steps,
                    "seq": sp.begin_seq,
                })
            ids = self._pad_ids(pre.blocks)
            rows = self._dispatch(
                "offload_gather", self._gather_jit,
                self._caches, self._stage(ids),
            )
            n = len(pre.blocks)
            host = {
                name: tuple(
                    np.asarray(self._host(z))[:n].copy()
                    for z in leaves
                )
                for name, leaves in rows.items()
            }
            store = _OffloadRecord(
                rows=host, n_blocks=n, cur_len=pre.cur_len,
            )
            self._offloaded[req.rid] = store
        self._set_active(pre.slot, False)
        self._m_preemptions.inc()
        self._m_offload_blocks.inc(n)
        self._m_offload_bytes.inc(store.nbytes())
        logger.info(
            "preempted request %d (priority %d): %d blocks offloaded "
            "to host, slot %d freed", req.rid, req.priority, n, pre.slot,
        )

    def _resume(self, adm: Admission) -> None:
        """Restore an offloaded request into its fresh allocation:
        scatter the host rows into the new table's leading blocks and
        re-arm the slot's cursor/last-token/temperature. Bit-exact —
        the restored rows are bitwise the offloaded ones and greedy
        decode is a pure function of (weights, K/V, cursor, last)."""
        req = adm.req
        store = self._offloaded.pop(req.rid)
        with self._tracer.span(
            "serve.resume", rid=req.rid, blocks=store.n_blocks,
        ) as sp:
            rec = self._fr(req.rid)
            if rec is not None:
                rec["resumes"].append({
                    "blocks": store.n_blocks, "cur_len": store.cur_len,
                    "step": self.scheduler._steps,
                    "seq": sp.begin_seq,
                })
            n = store.n_blocks
            ids = self._pad_ids(adm.blocks[:n])
            Tb = len(ids)
            rows = {}
            for name, leaves in store.rows.items():
                staged = []
                for hz in leaves:
                    pz = np.zeros((Tb,) + hz.shape[1:], hz.dtype)
                    pz[:n] = hz
                    staged.append(self._stage(pz))
                rows[name] = tuple(staged)
            self._caches = self._dispatch(
                "resume_scatter", self._scatter_jit,
                self._caches, self._stage(ids), rows,
            )
            mask = np.zeros((self.num_slots,), bool)
            mask[adm.slot] = True
            r_len = np.zeros((self.num_slots,), np.int32)
            r_len[adm.slot] = store.cur_len
            r_last = np.zeros((self.num_slots,), np.int32)
            r_last[adm.slot] = req.tokens[-1]
            r_temps = np.zeros((self.num_slots,), np.float32)
            r_temps[adm.slot] = req.temperature
            self._lengths, self._last, self._temps = self._dispatch(
                "resume_state", self._resume_state_jit,
                self._lengths, self._last, self._temps,
                self._stage_slots(mask), self._stage_slots(r_len),
                self._stage_slots(r_last),
                self._stage_slots(r_temps),
            )
        self._set_active(adm.slot, True)
        self._m_resumes.inc()
        logger.info(
            "resumed request %d into slot %d (%d blocks restored, "
            "cursor %d)", req.rid, adm.slot, n, store.cur_len,
        )

    def _sp_eligible(self, a: Admission) -> bool:
        """Does this fresh admission take the sequence-parallel prefill
        path? Long cold prompts only — a prefix hit's shared blocks
        already paid most of the prefill, and the SP pad length must
        fit the model (else fall back, LOUDLY: silence here would hide
        that the knob the caller reached for is not engaging)."""
        if self.sp_mesh is None or a.shared_len:
            return False
        p = len(a.req.prompt)
        if p < self.sp_threshold:
            return False
        from elephas_tpu.serving.sp_prefill import sp_pad_len

        S = sp_pad_len(p, self.sp_mesh.shape[self.sp_axis], self.maxlen)
        if S is None:
            logger.warning(
                "sp_prefill: prompt of %d tokens has no power-of-two "
                "pad length inside maxlen=%d — falling back to the "
                "single-device prefill path for request %d",
                p, self.maxlen, a.req.rid,
            )
            return False
        return True

    def _sp_staged_weights(self):
        """The engine's weights replicated over the SP mesh (lazy,
        dropped by :meth:`refresh_weights`): engine weights may be
        COMMITTED to the default device (e.g. values assigned off a
        training mesh), and a committed single-device argument refuses
        to enter a program whose shard_map spans the SP mesh."""
        if self._sp_weights is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.sp_mesh, P())
            self._sp_weights = {
                path: jax.device_put(w, rep)
                for path, w in self._weights.items()
            }
        return self._sp_weights

    def _sp_prefill(self, a: Admission):
        """Prefill one long prompt over the SP mesh: ONE sharded
        forward computes every position's K/V and logits, the rows
        land in the slot's reserved blocks via the resume scatter, and
        the first token samples from the prompt-end logits row. Decode
        then proceeds unmeshed, indistinguishable from a chunk-prefilled
        slot (token-exact at temperature 0)."""
        import jax.numpy as jnp

        from elephas_tpu.serving.sp_prefill import sp_pad_len

        req = a.req
        p = len(req.prompt)
        sp_w = self.sp_mesh.shape[self.sp_axis]
        S = sp_pad_len(p, sp_w, self.maxlen)
        tokens = np.zeros((1, S), np.int32)
        tokens[0, :p] = req.prompt
        n_res = blocks_for(p, self.block_size)
        ids = self._pad_ids(a.blocks[:n_res])
        Tb = len(ids)
        bs = self.block_size
        with self._tracer.span(
            "serve.sp_prefill", rid=req.rid, prompt=p, padded=S,
            shards=int(sp_w), mechanism=self.sp_mechanism,
        ) as sp:
            rec = self._fr(req.rid)
            if rec is not None:
                rec["sp_prefill"] = {
                    "padded": int(S), "shards": int(sp_w),
                    "mechanism": self.sp_mechanism,
                    "step": self.scheduler._steps,
                    "seq": sp.begin_seq,
                }
            kv, row = self._dispatch(
                "sp_prefill", self._sp_jit,
                self._sp_staged_weights(), jnp.asarray(tokens),
                np.int32(p),
            )
            # hop the K/V rows home through HOST memory (exactly how
            # preemption-resume stages its rows) and land them through
            # the UNMESHED scatter program — see sp_step's docstring.
            # The hop must NOT use device_put: that returns COMMITTED
            # arrays, committedness is part of jit cache keys, and one
            # committed leaf reaching the pool recompiles every
            # downstream program on its next dispatch.
            span = Tb * bs
            rows = {}
            for name, (kr, vr) in kv.items():
                hk = np.asarray(kr)
                hv = np.asarray(vr)
                if span <= S:
                    hk, hv = hk[:span], hv[:span]
                else:
                    pad = ((0, span - S), (0, 0), (0, 0))
                    hk = np.pad(hk, pad)
                    hv = np.pad(hv, pad)
                # sentinel-padded ids drop the bucketed tail; garbage
                # rows past the prompt land inside the request's OWN
                # reservation, where rewrite-before-visible covers them
                hk = hk.reshape(Tb, bs, *hk.shape[1:])
                hv = hv.reshape(Tb, bs, *hv.shape[1:])
                if self.kv_dtype == "fp":
                    rows[name] = (self._stage(hk), self._stage(hv))
                else:
                    # quantized arena: the landing rows must be codes
                    # + scales (the pool's stored layout) — host-side
                    # quantization matches the device programs'
                    # write-path math
                    hk, hks = quantize_rows_np(hk, self.kv_dtype)
                    hv, hvs = quantize_rows_np(hv, self.kv_dtype)
                    rows[name] = (
                        self._stage(hk), self._stage(hv),
                        self._stage(hks), self._stage(hvs),
                    )
            self._caches = self._dispatch(
                "resume_scatter", self._scatter_jit,
                self._caches, self._stage(ids), rows,
            )
            tok_dev, self._key = self._dispatch(
                "sp_sample", self._sp_sample_jit,
                self._stage(np.asarray(row)),
                jnp.full((1,), req.temperature, jnp.float32),
                self._key,
            )
            tok = int(np.asarray(tok_dev))
            mask = np.zeros((self.num_slots,), bool)
            mask[a.slot] = True
            r_len = np.zeros((self.num_slots,), np.int32)
            r_len[a.slot] = p
            r_last = np.zeros((self.num_slots,), np.int32)
            r_last[a.slot] = tok
            r_temps = np.zeros((self.num_slots,), np.float32)
            r_temps[a.slot] = req.temperature
            self._lengths, self._last, self._temps = self._dispatch(
                "resume_state", self._resume_state_jit,
                self._lengths, self._last, self._temps,
                self._stage_slots(mask), self._stage_slots(r_len),
                self._stage_slots(r_last),
                self._stage_slots(r_temps),
            )
        self.scheduler.on_prefill_complete(req)
        self._set_active(a.slot, True)
        self._note_prefill(req, f"sp{S}")
        self._emit(req, tok)
        return [(req, req.tokens[-1], req.done)]

    def _note_prefill(self, req: Request, bucket) -> None:
        """One histogram observation per completed prefill, labeled by
        the prompt's SIZE CLASS — the prompt-bucket ladder entry
        covering it, or ``sp<S>`` for an SP prefill (ISSUE 11
        telemetry). Chunked/paged prefills compile per chunk width,
        so this classifies the prompt, not the compiled program."""
        self._mf_prefill_tokens.labels(
            engine=self.telemetry_label, bucket=str(bucket)
        ).observe(len(req.prompt))

    def _admit_wave_paged(self, plan: list[Admission]):
        """Execute one paged admission wave: resumes restore their
        offloaded state (no prefill), fresh admissions prefill their
        un-shared suffix through the paged chunk program — whole
        suffix in one bucketed-width call, or budgeted chunks under
        ``prefill_chunk``. Prefix hits need NO device copy: the shared
        blocks already sit in the slot's table. Long cold prompts take
        the sequence-parallel path when ``sp_prefill`` is armed
        (:meth:`_sp_prefill`) — chunk budgets do not apply to them
        (the SP dispatch IS the bounded unit of work)."""
        emitted: list[tuple[Request, int, bool]] = []
        for a in plan:
            if a.resume is not None:
                self._resume(a)
        fresh = []
        for a in plan:
            if a.resume is not None:
                continue
            if self._sp_eligible(a):
                emitted.extend(self._sp_prefill(a))
            else:
                fresh.append(a)
        if self.prefill_chunk:
            for a in fresh:
                self._prefilling[a.slot] = [a, a.shared_len]
            return emitted
        by_width: dict[int, list] = {}
        for a in fresh:
            suffix = len(a.req.prompt) - a.shared_len
            by_width.setdefault(
                self.scheduler.bucket_for(suffix), []
            ).append((a, a.shared_len, suffix))
        for width in sorted(by_width):
            emitted.extend(self._run_chunk(by_width[width], width))
        return emitted

    def _admit_wave(self, plan: list[Admission]):
        """Execute one admission wave. Without chunking: full-bucket
        prefill for the cold requests (legacy wave), and for prefix
        hits ONE fused copy+suffix-chunk call per suffix bucket. With
        chunking: the wave's copies land NOW in one standalone
        copy-program call (the donors are only pinned through this
        wave — a budget-deferred chunk must not read a maybe-evicted
        donor later), then everything queues for budgeted chunks."""
        emitted: list[tuple[Request, int, bool]] = []
        copies = [a for a in plan if a.donor_slot is not None]
        if self.prefill_chunk:
            if copies:
                src, mask, clen = self._copy_vectors(copies)
                self._caches = self._dispatch(
                    "prefix_copy", self._copy_jit,
                    self._caches, self._stage_slots(src),
                    self._stage_slots(mask), self._stage_slots(clen),
                )
            for a in plan:
                self._prefilling[a.slot] = [a, a.reuse_len]
            return emitted
        cold = [a.req for a in plan if a.donor_slot is None]
        if cold:
            self._prefill_wave(cold)
            emitted.extend(
                (req, req.tokens[-1], req.done) for req in cold
            )
        # fused copy + suffix-only prefill of the hits, one chunk call
        # per suffix bucket (widths stay inside the closed ladder)
        by_width: dict[int, list] = {}
        for a in copies:
            suffix = len(a.req.prompt) - a.reuse_len
            by_width.setdefault(
                self.scheduler.bucket_for(suffix), []
            ).append((a, a.reuse_len, suffix))
        for width in sorted(by_width):
            emitted.extend(self._run_chunk(
                by_width[width], width,
                copies=[a for a, _p, _t in by_width[width]],
            ))
        return emitted

    def _prefill_progress(self):
        """Spend this step's prefill token budget on chunk calls: every
        mid-prefill slot advances by up to ``prefill_chunk`` tokens per
        call, calls repeat until the budget is spent or the queue
        drains. Decode windows run BETWEEN these budgeted slices — the
        whole point: a long prompt streams in without stalling in-flight
        requests' next tokens."""
        emitted: list[tuple[Request, int, bool]] = []
        if not self._prefilling:
            return emitted
        budget = self._prefill_budget
        served: set[int] = set()
        while self._prefilling and budget > 0:
            # the budget caps TOTAL prefill tokens this step, not per
            # call: with several long prompts mid-prefill, slots beyond
            # the budget wait for the next step (lowest slot first,
            # deterministic) — otherwise N concurrent arrivals would
            # cost N×chunk per step and in-flight inter-token latency
            # would scale with arrival count, the exact stall this
            # budget exists to bound
            items = []
            for slot in sorted(self._prefilling):
                if budget <= 0:
                    break
                adm, progress = self._prefilling[slot]
                take = min(
                    self.prefill_chunk, len(adm.req.prompt) - progress
                )
                items.append((adm, progress, take))
                served.add(slot)
                budget -= take
            emitted.extend(self._run_chunk(items, self.prefill_chunk))
            for adm, progress, take in items:
                if adm.slot in self._prefilling:
                    self._prefilling[adm.slot][1] = progress + take
        stalled = sum(1 for s in self._prefilling if s not in served)
        if stalled:
            # chunk-budget stall: slots that got NO chunk this step and
            # wait for the next one — the bounded-latency trade the
            # budget exists to make, but a rising rate means arrivals
            # outpace the budget. Slots that advanced this step are not
            # stalled even if they remain mid-prefill.
            self._m_prefill_stalls.inc(stalled)
        return emitted

    def _note_admissions(self, plan) -> None:
        """Per-tenant admitted counters (ISSUE 10) — fresh admissions
        only; a preemption resume was already counted when it first
        entered a slot."""
        if self.policy is None:
            return
        for a in plan:
            if a.resume is None:
                self._tenant_child(
                    self._mf_tenant_admitted, a.req.tenant
                ).inc()

    def step(self) -> list[tuple[Request, int, bool]]:
        """One engine iteration: admission of waiting requests into
        free slots (prefix-cache copies + prefill — full-wave, or
        budgeted chunks interleaved with decode), then one arena-wide
        decode window of ``steps_per_sync`` steps over the slots whose
        prefill has completed. Returns ``(request, token, done)``
        triples in generation order (a request can appear several
        times: its prefill token plus one per window position); the
        ``done`` flag is per-TOKEN — True only on a request's final
        token, so stream consumers can stop at it without dropping
        tokens."""
        emitted: list[tuple[Request, int, bool]] = []
        if self.paged:
            plan, preempts = self.scheduler.admit_paged(
                prefilling=frozenset(self._prefilling)
            )
            # offloads FIRST: victims' device rows must be read before
            # any admission's prefill (or resume scatter) writes the
            # pool — the gather is dispatched against the current pool
            # value, so ordering here is the whole correctness story
            for pre in preempts:
                self._offload(pre)
            if plan:
                self._note_admissions(plan)
                self._trace_admissions(plan)
                emitted.extend(self._admit_wave_paged(plan))
        else:
            plan = self.scheduler.admit()
            if plan:
                # admission emissions land before any decode token, so
                # req.done there is the prefill token's own flag
                self._note_admissions(plan)
                self._trace_admissions(plan)
                emitted.extend(self._admit_wave(plan))
        emitted.extend(self._prefill_progress())
        if not any(
            slot not in self._prefilling for slot in self.scheduler.active
        ):
            return emitted
        self._m_decode_windows.inc()
        if self.speculative:
            emitted.extend(self._spec_decode_phase())
        else:
            emitted.extend(self._decode_window())
        return emitted

    def _decode_window(self):
        """One arena-wide plain decode window of ``steps_per_sync``
        steps — the non-speculative decode phase, and the speculative
        engine's fallback when no slot drafted this round."""
        if self.speculative and self._spec_dirty:
            self._refresh_decode_state()
        emitted: list[tuple[Request, int, bool]] = []
        with self._tracer.span(
            "serve.decode_window", steps=self.steps_per_sync,
            active=len(self.scheduler.active),
        ):
            if self.paged:
                (self._caches, self._lengths, self._last, self._key,
                 window) = self._dispatch(
                    "paged_decode", self._paged_decode_jit,
                    self._weights, self._caches, self._staged_tables(),
                    self._lengths, self._last, self._temps,
                    self._sync_active(), self._key,
                )
            else:
                (self._caches, self._lengths, self._last, self._key,
                 window) = self._dispatch(
                    "decode", self._decode_jit,
                    self._weights, self._caches, self._lengths,
                    self._last, self._temps, self._sync_active(),
                    self._key, self._decode_span(),
                )
            toks = self._host(window)  # [steps_per_sync, num_slots]
            for i in range(self.steps_per_sync):
                if not self.scheduler.active:
                    break  # window tail decoded garbage for empty slots
                self.scheduler.note_step()
                for slot, req in sorted(self.scheduler.active.items()):
                    if slot in self._prefilling:
                        continue  # mid-prefill: no decode tokens yet
                    done = self._emit(req, int(toks[i, slot]))
                    emitted.append((req, req.tokens[-1], done))
        return emitted

    # -- speculative decoding (ISSUE 8) --------------------------------

    def _refresh_decode_state(self):
        """Re-stage the device length/last vectors from host truth.
        Verify rounds advance positions host-side only (resident length
        = prompt + generated - 1, the invariant preemption's ``cur_len``
        already relies on), so before a plain decode window reads the
        device vectors they must be rebuilt. Mid-prefill and idle slots
        stage zeros — the decode active mask excludes them, and a later
        chunk finalize sets their real state on device."""
        lengths = np.zeros((self.num_slots,), np.int32)
        last = np.zeros((self.num_slots,), np.int32)
        for slot, req in self.scheduler.active.items():
            if slot in self._prefilling or not req.tokens:
                continue
            lengths[slot] = len(req.prompt) + len(req.tokens) - 1
            last[slot] = req.tokens[-1]
        self._lengths = self._stage_slots(lengths)
        self._last = self._stage_slots(last)
        self._spec_dirty = False

    def _spec_decode_phase(self):
        """One speculative decode round: collect drafts for every
        decoding slot (throttle- and budget-capped), then either run
        ONE batched verify forward over the whole window — emitting
        the accepted prefix + bonus token per slot — or, when nobody
        drafted (throttled, no n-gram match, budget exhausted), fall
        back to one plain ``steps_per_sync`` decode window so
        speculation-hostile phases keep the multi-step amortization."""
        items = []
        for slot in sorted(self.scheduler.active):
            if slot in self._prefilling:
                continue
            req = self.scheduler.active[slot]
            remaining = req.max_new_tokens - len(req.tokens)
            cursor = len(req.prompt) + len(req.tokens) - 1
            # the verify window feeds 1 + n_drafts tokens at positions
            # cursor.. and emits at most n_drafts + 1 tokens: drafts
            # are capped so writes stay inside the slot's row (and its
            # paged block reservation) and emissions inside the budget
            k_cap = min(
                self.spec_k, remaining - 1, self.maxlen - 1 - cursor
            )
            if k_cap >= 1 and self._spec_throttle.should_draft(req.rid):
                items.append((slot, req, k_cap))
        proposals = (
            self._drafter.propose_batch(items) if items else {}
        )
        # defend the extension point: a custom drafter returning MORE
        # than its k (which sizes the packed window and the accept
        # loop) or drafts for slots it was never asked about (which
        # would bypass the throttle and the budget/maxlen caps) must
        # not corrupt the round — clip to each item's own cap, drop
        # uninvited slots
        caps = {slot: k for slot, _req, k in items}
        proposals = {
            slot: list(d)[: caps[slot]]
            for slot, d in proposals.items()
            if slot in caps and d
        }
        drafted = sum(len(d) for d in proposals.values())
        if drafted == 0:
            return self._decode_window()
        return self._verify_round(proposals, drafted)

    def _verify_round(self, proposals, drafted: int):
        """Dispatch one batched verify forward and commit its verdict:
        per slot, accept the longest draft prefix matching the model's
        own sampled tokens, emit those plus the bonus token, and roll
        the resident length back over the rejected tail (host-side
        cursor arithmetic — the garbage K/V is rewritten before any
        query can see it; paged tails stay inside already-reserved
        blocks, so the allocator is never touched mid-step)."""
        W = self.spec_k + 1
        # one packed [num_slots, W+3] upload: tokens | offset | n_fed
        # | active — see the program definition for why
        packed = np.zeros((self.num_slots, W + 3), np.int32)
        verifying = []
        for slot in sorted(self.scheduler.active):
            if slot in self._prefilling:
                continue
            req = self.scheduler.active[slot]
            drafts = proposals.get(slot, [])
            packed[slot, 0] = req.tokens[-1]
            packed[slot, 1:1 + len(drafts)] = drafts
            packed[slot, W] = len(req.prompt) + len(req.tokens) - 1
            packed[slot, W + 1] = 1 + len(drafts)
            packed[slot, W + 2] = 1
            verifying.append((slot, req, drafts))
        emitted: list[tuple[Request, int, bool]] = []
        with self._tracer.span(
            "serve.verify", slots=len(verifying), drafted=drafted,
            k=self.spec_k,
        ) as span:
            if self.paged:
                self._caches, self._key, sampled = self._dispatch(
                    "spec_verify", self._verify_jit,
                    self._weights, self._caches, self._staged_tables(),
                    self._stage_slots(packed), self._temps, self._key,
                )
            else:
                # attended span covers the window's deepest write:
                # offset + n_fed over the verifying slots
                att_span = self._fixed_span(max(
                    int(packed[s, W]) + int(packed[s, W + 1])
                    for s, _r, _d in verifying
                )) if verifying else None
                self._caches, self._key, sampled = self._dispatch(
                    "spec_verify", self._verify_jit,
                    self._weights, self._caches,
                    self._stage_slots(packed), self._temps, self._key,
                    att_span,
                )
            toks = self._host(sampled)  # [num_slots, W]
            self.scheduler.note_step()
            accepted_total = 0
            for slot, req, drafts in verifying:
                t = toks[slot]
                a = 0
                while a < len(drafts) and drafts[a] == int(t[a]):
                    a += 1
                # accepted drafts + the model's bonus token, in order;
                # a mid-window EOS finish discards the rest
                n_emitted = 0
                for j in range(a + 1):
                    done = self._emit(req, int(t[j]))
                    emitted.append((req, req.tokens[-1], done))
                    n_emitted += 1
                    if done:
                        break
                # count only accepted drafts that actually EMITTED —
                # an EOS inside the window discards the matched tail,
                # and those drafts saved no decode step (the counter's
                # promise); the throttle gets the same truthful figure
                a = min(a, n_emitted)
                accepted_total += a
                req.spec_drafted += len(drafts)
                req.spec_accepted += a
                tripped = self._spec_throttle.note(
                    req.rid, len(drafts), a
                )
                if tripped:
                    self._m_spec_throttled.inc()
                seq = self._tracer.emit(
                    "serve.spec_verify", rid=req.rid,
                    drafted=len(drafts), accepted=a,
                    throttled=self._spec_throttle.throttled(req.rid),
                    step=self.scheduler._steps,
                )
                rec = self._fr(req.rid)
                if rec is not None:
                    rec["spec_rounds"].append({
                        "drafted": len(drafts), "accepted": a,
                        "throttled": self._spec_throttle.throttled(
                            req.rid
                        ),
                        "step": self.scheduler._steps, "seq": seq,
                    })
                    # a request that FINISHED inside this round was
                    # filed by _fr_finish before these per-round
                    # increments landed — refresh the totals so the
                    # record always agrees with its own spec_rounds
                    # (same dict object whether live or filed)
                    rec["spec_drafted"] = req.spec_drafted
                    rec["spec_accepted"] = req.spec_accepted
            span.set(accepted=accepted_total)
        self._m_spec_drafted.inc(drafted)
        self._m_spec_accepted.inc(accepted_total)
        self._m_spec_rounds.inc()
        self._spec_dirty = True
        return emitted

    def stream(self):
        """Drive the engine until the queue drains, yielding
        ``(request_id, token, done)`` as tokens land — the per-request
        token stream. More requests may be submitted while consuming
        (they join the next admission wave)."""
        while self.scheduler.has_work:
            for req, token, done in self.step():
                yield req.rid, token, done

    def run(self, requests=None) -> dict[int, np.ndarray]:
        """Convenience batch driver: optionally submit ``requests``
        (an iterable of ``(prompt, max_new_tokens)`` pairs or kwargs
        dicts), drive the engine until idle, and return
        ``{request_id: full token sequence (prompt + generated)}``.

        Requests submitted through THIS call are exempt from the
        bounded finished-registry eviction until it returns — a huge
        batch cannot silently lose its own oldest results."""
        submitted: list[Request] = []
        if requests is not None:
            for r in requests:
                if isinstance(r, dict):
                    submitted.append(self.submit(**r))
                else:
                    prompt, max_new = r
                    submitted.append(self.submit(prompt, max_new))
        protected = {r.rid for r in submitted} - self._protected
        self._protected |= protected
        try:
            drained: dict[int, np.ndarray] = {}
            while self.scheduler.has_work:
                for req, _tok, done in self.step():
                    if done:
                        drained[req.rid] = np.asarray(
                            req.full_sequence, np.int32
                        )
        finally:
            self._protected -= protected
            self._evict_finished()  # deferred trim, still loud
        return drained

    # -- lifecycle control: cancel + live migration (ISSUE 14) ---------

    def _detach(self, req: Request, reason: str) -> None:
        """Shared bookkeeping for a request leaving the engine before
        completion (cancel / migration export): policy + spec-throttle
        accounting drop and the flight record files with ``reason``."""
        if self.policy is not None:
            self.policy.on_finish(req)
        if self._spec_throttle is not None:
            self._spec_throttle.forget(req.rid)
        self._fr_finish(req, reason)

    def _find_slot(self, rid: int) -> int | None:
        return next(
            (s for s, r in self.scheduler.active.items()
             if r.rid == rid),
            None,
        )

    def _notify_stream_end(self, req: Request) -> None:
        """Tell a request's live stream it ENDED without a final
        engine token — ``on_token(None, True)``. Without this, a
        consumer blocking on the token stream (the gateway's SSE/JSON
        handlers) waits forever when the request is cancelled or
        migrated away mid-flight: those paths flip ``req.done``
        without ever invoking the callback."""
        cb = req.on_token
        if cb is not None:
            try:
                cb(None, True)
            except BaseException:
                logger.warning(
                    "request %d stream-end callback failed",
                    req.rid, exc_info=True,
                )

    def cancel(self, rid: int) -> bool:
        """Abort one in-flight request and reclaim its slot/blocks
        NOW — a disconnected SSE client's request must not decode to
        completion into a queue nobody reads (the gateway wires client
        aborts here; the router's re-drive path uses it too). Works on
        every engine config: a waiting request just leaves the queue, a
        preempted one drops its host offload record, an active one
        frees its slot (and block table, paged) at the next step
        boundary — deterministic host bookkeeping only, no device
        program runs. Returns True when the rid was live (its
        ``req.done`` flips True with ``req.error`` set to
        :class:`RequestCancelled`; generated-so-far tokens are kept),
        False when it was unknown or already finished.

        Gang contract: like :meth:`submit`, every gang process must
        issue the identical cancel sequence at the identical step
        boundaries — cancellation reshapes the admission schedule."""
        rid = int(rid)
        sched = self.scheduler
        req = sched.remove_waiting(rid)
        if req is not None:
            # a preempted victim waiting to resume also drops its
            # host-offloaded K/V here
            self._offloaded.pop(rid, None)
        else:
            slot = self._find_slot(rid)
            if slot is None:
                return False
            req = sched.active[slot]
            self._prefilling.pop(slot, None)
            self._stale_prefill.discard(slot)
            sched.reclaim(slot)
            self._set_active(slot, False)
        req.done = True
        req.error = RequestCancelled(f"request {rid} cancelled")
        # a live stream must UNBLOCK, not hang: cancel never delivers
        # a final token, so send the explicit end sentinel
        self._notify_stream_end(req)
        self._m_cancelled.inc()
        self._tracer.emit(
            "serve.cancel", rid=rid, tokens=len(req.tokens),
            step=sched._steps,
        )
        self._detach(req, "cancelled")
        self.finished[rid] = req
        self._evict_finished()
        return True

    def score(self, prompt, completion) -> dict:
        """Log-probabilities of ``completion`` given ``prompt`` in ONE
        forward pass (ISSUE 19): scoring is verify-without-accept —
        the sequence ``prompt + completion[:-1]`` feeds through the
        existing verify/chunk program shape on lane 0, and logits row
        ``j`` scores the token at position ``j+1``. The forward runs
        against a NON-donated copy of the live arena whose update is
        discarded, so scoring never perturbs in-flight serving state
        (no allocation, no cursor movement, no PRNG consumption).

        Returns ``{"logprobs": [per-completion-token logprob],
        "total_logprob", "greedy_tokens": [argmax token per position],
        "agreement": fraction of completion tokens matching greedy}``
        — greedy tokens make this the fp-oracle token-agreement probe
        the quant bench gates consume (temperature-0 caveat: agreement
        compares argmax, so it is exactly what greedy decode would
        emit position-by-position given this prefix).

        Compiled per (width bucket[, table/span bucket]) — the same
        closed ladders the serving programs use, so a scoring workload
        cannot grow the compile set unboundedly. Requires ``prompt``
        and ``completion`` non-empty and their sum within ``maxlen``.
        """
        prompt = [int(t) for t in prompt]
        completion = [int(t) for t in completion]
        if not prompt:
            raise ValueError("score() needs a non-empty prompt")
        if not completion:
            raise ValueError("score() needs a non-empty completion")
        total = len(prompt) + len(completion)
        if total > self.maxlen:
            raise ValueError(
                f"prompt ({len(prompt)}) + completion "
                f"({len(completion)}) exceeds maxlen ({self.maxlen})"
            )
        seq = prompt + completion
        n = total - 1  # fed positions; row j scores seq[j+1]
        width = self.scheduler.bucket_for(n)
        tokens = np.zeros((self.num_slots, width), np.int32)
        tokens[0, :n] = seq[:n]
        targets = np.zeros((width,), np.int32)
        targets[:n] = seq[1:]
        clens = np.zeros((self.num_slots,), np.int32)
        clens[0] = n
        act = np.zeros((self.num_slots,), bool)
        act[0] = True
        if self.paged:
            nb = blocks_for(n, self.block_size)
            if nb > self.num_blocks:
                raise ValueError(
                    f"scoring {n} positions needs {nb} blocks — more "
                    f"than the pool's {self.num_blocks}"
                )
            Tb = table_bucket_for(nb, self._tbuckets)
            # scratch arange table: the one-hot writes land only in
            # the DISCARDED pool copy, so any block ids are safe
            tab = np.full((self.num_slots, Tb), self.num_blocks,
                          np.int32)
            tab[0, :nb] = np.arange(nb, dtype=np.int32)
            tlp, greedy = self._dispatch(
                "score", self._score_jit,
                self._weights, self._caches, self._stage(tab),
                self._stage(tokens), self._stage_slots(clens),
                self._stage_slots(act), self._stage(targets),
            )
        else:
            span = (
                span_bucket_for(n, self._sbuckets)
                if self.attention == "flash" else None
            )
            tlp, greedy = self._dispatch(
                "score", self._score_jit,
                self._weights, self._caches, self._stage(tokens),
                self._stage_slots(clens), self._stage_slots(act),
                self._stage(targets), span,
            )
        tlp = np.asarray(self._host(tlp))
        greedy = np.asarray(self._host(greedy))
        p = len(prompt)
        lps = [float(x) for x in tlp[p - 1:n]]
        g = [int(t) for t in greedy[p - 1:n]]
        agreed = sum(1 for a, b in zip(g, completion) if a == b)
        self._m_score_requests.inc()
        self._tracer.emit(
            "serve.score", prompt_tokens=p,
            completion_tokens=len(completion),
            agreement=agreed / len(completion),
        )
        return {
            "logprobs": lps,
            "total_logprob": float(sum(lps)),
            "greedy_tokens": g,
            "agreement": agreed / len(completion),
        }

    def export_request(self, rid: int, *,
                       notify_stream: bool = False) -> dict:
        """Freeze one live request and hand back its **migration
        record** (ISSUE 14): a host-native dict — prompt, generated
        tokens, budget/sampling/tenant knobs, and (warm path) the
        preemption offload rows (dense per-layer K/V blocks) plus the
        cursor state — that :meth:`import_request` on ANOTHER replica
        resumes bit-exact at temperature 0. PR 7's offload record IS
        the serialization format; this method just detaches it from
        the engine. The request leaves this engine entirely (policy
        accounting dropped, flight record filed as ``migrated`` — it
        is NOT in ``finished``, it lives on elsewhere).

        Warm export (K/V travels) needs a paged engine and a request
        holding at least one generated token; waiting, mid-prefill,
        and tokenless requests export COLD (the target re-prefills —
        nothing resident is worth moving). An in-flight fixed-arena
        request with tokens refuses loudly: the fixed arena has no
        block-granular gather. Raises ``KeyError`` for a rid that is
        not live here. Wire encoding lives in
        :mod:`elephas_tpu.fleet.migration`.

        ``notify_stream=True`` sends the exported request's live
        ``on_token`` stream the ``(None, True)`` end sentinel — the
        wire-migration shape (gateway ``/v1/requests/{rid}/export``),
        where no callback travels and a local consumer blocking on
        the stream would otherwise hang forever. The in-process fleet
        router keeps the default: it re-attaches the SAME stream on
        import, so the tokens must keep flowing to it."""
        rid = int(rid)
        sched = self.scheduler
        store = self._offloaded.pop(rid, None)
        if store is not None:
            # already preempted: its offload record is the migration
            # payload, ready-made (victims always wait in the queue)
            req = sched.remove_waiting(rid)
            assert req is not None  # preempted ⇒ waiting, invariant
            return self._export_payload(
                req, store, notify_stream=notify_stream
            )
        slot = self._find_slot(rid)
        if slot is not None:
            req = sched.active[slot]
            if slot not in self._prefilling and req.tokens:
                if not self.paged:
                    raise ValueError(
                        f"cannot warm-export in-flight request {rid} "
                        f"from a fixed-arena engine — block offload "
                        f"needs paged=True (cancel it or let it finish)"
                    )
                # force-preempt regardless of priority: drain has
                # authority pressure never does. The engine offloads
                # the device rows to host, then the record detaches
                # through the _offloaded branch above.
                pre = sched._preempt(req)
                self._offload(pre)
                return self.export_request(
                    rid, notify_stream=notify_stream
                )
            # mid-prefill / tokenless: partial rows are not a resumable
            # state — cold export, target prefills from scratch
            self._prefilling.pop(slot, None)
            self._stale_prefill.discard(slot)
            sched.reclaim(slot)
            self._set_active(slot, False)
            return self._export_payload(
                req, None, notify_stream=notify_stream
            )
        req = sched.remove_waiting(rid)
        if req is None:
            raise KeyError(f"request {rid} is not live on this engine")
        return self._export_payload(
            req, None, notify_stream=notify_stream
        )

    def _export_payload(self, req: Request, store, *,
                        notify_stream: bool = False) -> dict:
        self._detach(req, "migrated")
        if notify_stream:
            self._notify_stream_end(req)
        self._m_migrated_out.inc()
        self._m_export_bytes.inc(0 if store is None else store.nbytes())
        self._tracer.emit(
            "serve.export", rid=req.rid, warm=store is not None,
            n_blocks=0 if store is None else store.n_blocks,
            tokens=len(req.tokens), step=self.scheduler._steps,
        )
        return {
            # v2 (ISSUE 19): rows travel at the arena's STORED dtype
            # (fp pairs, or quantized code+scale 4-tuples), declared
            # by kv_dtype so an importer can refuse a mismatch before
            # touching array bytes; v1 records remain importable
            # v3 (ISSUE 20): weight_ver declares the K/V's generation —
            # warm rows computed under generation N are garbage under
            # N+1, so the importer refuses a non-zero mismatch loudly
            "version": 3,
            "kv_dtype": self.kv_dtype,
            "weight_ver": self.weight_version,
            "rid": int(req.rid),
            "prompt": [int(t) for t in req.prompt],
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "eos_id": None if req.eos_id is None else int(req.eos_id),
            "priority": int(req.priority),
            "tenant": req.tenant,
            "ttft_deadline_ms": req.ttft_deadline_ms,
            # trace context rides the record so the migrated half of
            # the lifecycle joins the same story on a merged timeline
            "trace": telemetry.current_trace(),
            "block_size": self.block_size,
            "cur_len": 0 if store is None else store.cur_len,
            "n_blocks": 0 if store is None else store.n_blocks,
            "rows": {} if store is None else dict(store.rows),
        }

    def import_request(self, record: dict, on_token=None) -> Request:
        """Adopt a migration record exported by another replica
        (ISSUE 14). A warm record (``n_blocks > 0``) re-enters through
        the preemption-resume path: the K/V rows park as a host
        offload record, the request waits at the queue FRONT, and the
        next admission scatters the rows into a fresh block table and
        re-arms the cursor — bit-exact at temperature 0 by the same
        argument as local preempt/resume (greedy decode is a pure
        function of weights + K/V + cursor + last token; replicas
        serve identical weights). A cold record is an ordinary
        re-submission. ``on_token`` re-attaches the caller's stream
        (callbacks never travel on the wire). Temp>0 streams re-key on
        THIS engine's PRNG stream — deterministic per config, but not
        the source engine's continuation (same caveat as chunked
        prefill).

        Validates loudly: version, maxlen fit, rid not already live
        here, tenant known to this engine's policy, and — warm —
        paged target, matching block size/geometry, matching
        ``kv_dtype`` (quantized blocks are only bit-portable between
        arenas storing the same dtype — v1/fp records refuse into a
        quantized arena and vice versa), and the ``cur_len == prompt
        + generated - 1`` resume invariant."""
        if int(record.get("version", -1)) not in (1, 2, 3):
            raise ValueError(
                f"unknown migration record version "
                f"{record.get('version')!r} (this engine speaks "
                f"v1..v3)"
            )
        sched = self.scheduler
        rid = int(record["rid"])
        prompt = tuple(int(t) for t in record["prompt"])
        tokens = [int(t) for t in record["tokens"]]
        max_new = int(record["max_new_tokens"])
        if not prompt:
            raise ValueError("migration record has an empty prompt")
        if len(prompt) + max_new > self.maxlen:
            raise ValueError(
                f"record needs prompt ({len(prompt)}) + budget "
                f"({max_new}) <= maxlen ({self.maxlen})"
            )
        if (
            rid in self._offloaded
            or rid in self.finished
            or any(r.rid == rid for r in sched.waiting)
            or any(r.rid == rid for r in sched.active.values())
        ):
            # exactly-once: live rids always refuse; served rids
            # refuse for as long as the BOUNDED finished registry
            # remembers them (best-effort replay guard — the wire
            # protocol's real guarantee is that export detaches the
            # record from its source exactly once)
            raise ValueError(
                f"request {rid} is already live (or was already "
                f"served) on this engine — a record must be imported "
                f"exactly once"
            )
        tenant = record.get("tenant")
        if tenant is not None and (
            self.policy is None or not self.policy.knows(tenant)
        ):
            raise ValueError(
                f"record carries tenant {tenant!r} unknown to this "
                f"engine's policy — fleet replicas must declare "
                f"identical tenants"
            )
        rows = record.get("rows") or {}
        n_blocks = int(record.get("n_blocks") or 0)
        warm = n_blocks > 0
        if not warm and tokens:
            # a cold import re-prefills the PROMPT only: pre-set
            # generated tokens would interleave with tokens decoded
            # from a context that never saw them, and silently eat
            # the budget — no legitimate export produces this shape
            raise ValueError(
                f"cold record (n_blocks=0) carries {len(tokens)} "
                f"generated tokens — token-holding requests must "
                f"export WARM (K/V travels) or not at all"
            )
        if warm:
            if not self.paged:
                raise ValueError(
                    "warm migration record needs a paged target engine"
                )
            if int(record["block_size"]) != self.block_size:
                raise ValueError(
                    f"record block_size {record['block_size']} != this "
                    f"engine's {self.block_size} — K/V blocks are not "
                    f"geometry-portable"
                )
            rec_dtype = record.get("kv_dtype", "fp")
            if rec_dtype != self.kv_dtype:
                raise ValueError(
                    f"record kv_dtype {rec_dtype!r} != this engine's "
                    f"{self.kv_dtype!r} — quantized KV blocks are "
                    f"bit-portable only between arenas storing the "
                    f"same dtype (re-drive the request cold instead)"
                )
            # weight generation (ISSUE 20, v3): warm rows computed
            # under generation N are garbage under N+1 — resuming them
            # would silently break bit-exactness, the exact failure
            # this field exists to catch. 0 means "unversioned /
            # legacy record, cannot verify" (the shard-identity idiom):
            # refusal needs BOTH sides to claim a generation.
            rec_wver = int(record.get("weight_ver", 0))
            if rec_wver and self.weight_version and (
                rec_wver != self.weight_version
            ):
                raise ValueError(
                    f"record weight_ver {rec_wver} != this engine's "
                    f"weight_version {self.weight_version} — warm K/V "
                    f"from another weight generation cannot resume "
                    f"bit-exact (re-drive the request cold instead)"
                )
            arity = 2 if self.kv_dtype == "fp" else 4
            bad_arity = {
                name: len(leaves) for name, leaves in rows.items()
                if len(leaves) != arity
            }
            if bad_arity:
                raise ValueError(
                    f"record rows carry {bad_arity} arrays per layer "
                    f"— a {self.kv_dtype!r} arena stores {arity} "
                    f"(torn or mis-encoded record)"
                )
            if not tokens:
                raise ValueError(
                    "warm record without generated tokens — the resume "
                    "cursor math (last token re-arm) would be wrong"
                )
            cur_len = int(record["cur_len"])
            if cur_len != len(prompt) + len(tokens) - 1:
                raise ValueError(
                    f"corrupt record: cur_len {cur_len} != prompt "
                    f"({len(prompt)}) + generated ({len(tokens)}) - 1"
                )
            if n_blocks != blocks_for(cur_len, self.block_size):
                raise ValueError(
                    f"corrupt record: {n_blocks} blocks cannot cover "
                    f"cur_len {cur_len} at block_size {self.block_size}"
                )
            expected = {name for name, _h, _d in self.arena.specs}
            if set(rows) != expected:
                raise ValueError(
                    f"record layers {sorted(rows)} != this engine's "
                    f"{sorted(expected)} — different model architecture"
                )
            if blocks_for(
                len(prompt) + max_new, self.block_size
            ) > self.num_blocks:
                raise ValueError(
                    f"record can never fit this pool ({self.num_blocks}"
                    f" blocks) — route it to a larger replica"
                )
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new,
            temperature=float(record.get("temperature") or 0.0),
            eos_id=(
                None if record.get("eos_id") is None
                else int(record["eos_id"])
            ),
            priority=int(record.get("priority") or 0),
            tenant=tenant,
            ttft_deadline_ms=record.get("ttft_deadline_ms"),
            tokens=tokens,
            on_token=on_token,
        )
        req.submit_step = sched._steps
        # TTFT was (or will be) observed where the request FIRST ran;
        # submit_time stays None here so a migrated request's next
        # token never double-observes the TTFT histogram or SLO
        # counters on the adopting engine
        req.exemplar = {"rid": str(rid)}
        rec = self._fr_new(req)
        seq = self._tracer.emit(
            "serve.import", rid=rid, warm=warm, n_blocks=n_blocks,
            tokens=len(tokens), step=sched._steps,
        )
        if rec is not None:
            rec["submit_seq"] = seq
        if warm:
            host_rows = {
                name: tuple(
                    np.ascontiguousarray(a) for a in leaves
                )
                for name, leaves in rows.items()
            }
            self._offloaded[rid] = _OffloadRecord(
                rows=host_rows, n_blocks=n_blocks,
                cur_len=int(record["cur_len"]),
            )
            sched.adopt_preempted(req, int(record["cur_len"]))
        else:
            # scheduler.submit handles the policy's on_submit hook
            sched.submit(req)
        self._m_migrated_in.inc()
        return req

    # -- introspection -------------------------------------------------

    # Telemetry views (ISSUE 5 satellite): the registry counters are
    # the ONLY store — these attributes read them back, so stats(),
    # scrape(), and the bench can never drift apart. Under null mode
    # they read 0 (telemetry off zeroes reporting, never behavior).

    @property
    def total_generated(self) -> int:
        return int(self._m_tokens.value)

    @property
    def finished_count(self) -> int:
        return int(self._m_finished.value)

    @property
    def finished_evicted(self) -> int:
        return int(self._m_finished_evicted.value)

    def scrape(self, openmetrics: bool = False,
               full: bool = True) -> str:
        """This engine's registry rendered as Prometheus exposition
        text (the in-process scrape surface; the HTTP surface is the
        parameter server's ``GET /metrics``). Empty when the engine was
        constructed under telemetry null mode. ``openmetrics=True``
        renders the OpenMetrics flavor instead — histogram buckets
        carry their rid exemplars (ISSUE 12), so a TTFT p99 spike
        links straight to :meth:`explain`'s record of the request.

        ``full=False`` (ISSUE 14) narrows the exposition to THIS
        engine's own series (its ``engine=`` labels plus its
        scheduler's ``scheduler=`` labels) — the per-replica scrape
        shape a :class:`~elephas_tpu.telemetry.aggregate.FleetScraper`
        wants when several replicas share one process registry (a full
        render would make every instance's fleet view identical sums).
        Same ``only=`` filtering the PR-13 PS scrape-parity satellite
        introduced; no new metrics plumbing."""
        if openmetrics:
            if not full:
                raise ValueError(
                    "full=False is a 0.0.4-flavor filter — the "
                    "OpenMetrics surface renders the whole registry"
                )
            return telemetry.render_openmetrics(self._telemetry_registry)
        if not full:
            reg = self._telemetry_registry
            return telemetry.render(
                reg, only={"engine": self.telemetry_label}
            ) + telemetry.render(
                reg, only={"scheduler": self.scheduler.telemetry_label}
            )
        return telemetry.render(self._telemetry_registry)

    def prefix_warm_probe(self, prompt) -> int:
        """How many leading tokens of ``prompt`` the engine's prefix
        cache would serve without recompute — the pure cache-warmth
        probe (ISSUE 12 satellite; ROADMAP item 3's cache-aware-
        routing primitive). 0 on engines without a prefix cache. Pure
        and side-effect-free (no hit/LRU accounting, same contract as
        ``match()``), so probing at any rate never skews this
        engine's cache behavior, and by construction it equals the
        reuse length admission would then commit. NOT synchronized
        against a concurrently-stepping driver — on a gateway-driven
        engine, probe while holding the gateway's engine lock (the
        wire surfaces already do)."""
        prompt = np.asarray(prompt).reshape(-1)
        idx = self.scheduler.prefix_index
        if idx is not None:
            return idx.match_len(prompt)
        cache = self.scheduler.prefix_cache
        if cache is not None:
            return cache.match_len(prompt)
        return 0

    def explain(self, rid: int) -> dict:
        """The structured lifecycle record of one request (ISSUE 12):
        admission verdict + queue wait, admission kind/reuse length,
        prefill chunks, preempt/offload/resume, spec verify rounds,
        per-token step indices, first token, and finish reason — every
        entry stamped with the scheduler step and tracer sequence
        number it happened at (logical order; wall-derived fields are
        export-only). In-flight requests return their partial record
        (``finish`` is None); finished requests come from the bounded
        flight-recorder ring (last ``flight_recorder=`` lifecycles).

        Raises ``RuntimeError`` when the recorder is off (knob 0/None
        or the engine was built under telemetry null mode) and
        ``KeyError`` for an unknown/evicted rid. Served over the wire
        as ``GET /v1/requests/{rid}/trace``."""
        import copy

        if self._flight is None:
            raise RuntimeError(
                "flight recorder is off (flight_recorder=0/None, or "
                "the engine was built under telemetry null mode) — "
                "explain() has no lifecycle records to read"
            )
        rec = self._fr(int(rid))
        if rec is None:
            raise KeyError(
                f"no lifecycle record for request {rid} — never "
                f"submitted to this engine, or evicted from the "
                f"{self._flight.capacity}-record flight ring"
            )
        return copy.deepcopy(rec)

    def debug_snapshot(self) -> dict:
        """One structured snapshot of live engine state (ISSUE 12 —
        the gateway's ``GET /debug/engine``): slot map, waiting queue
        with per-request policy debt, block-pool occupancy, offloaded
        (preempted) requests, prefix cache/index summary, policy
        state (virtual counters), compiled-program counts, and the
        flight recorder's occupancy. Read-only host work — safe to
        call between steps at any cadence."""
        sched = self.scheduler
        slots = {}
        for slot, req in sorted(sched.active.items()):
            pre = self._prefilling.get(slot)
            slots[str(slot)] = {
                "rid": req.rid,
                "tenant": req.tenant,
                "prompt_tokens": len(req.prompt),
                "generated": len(req.tokens),
                "prefilling": pre is not None,
                "prefill_progress": pre[1] if pre is not None else None,
                "table_blocks": (
                    len(sched.tables.get(slot, ()))
                    if self.paged else None
                ),
            }
        from elephas_tpu.utils import backend_guard

        out = {
            "engine": self.telemetry_label,
            "steps": sched._steps,
            "num_slots": self.num_slots,
            "attention": self.attention,
            "kv_dtype": self.kv_dtype,
            "weight_version": self.weight_version,
            # the BENCH_r05 lesson at the serving surface: if backend
            # discovery fell back to CPU, say so HERE, not only in
            # bench JSON
            "backend_fallback": backend_guard.last_fallback(),
            "slots": slots,
            "waiting": sched.queue_snapshot(),
            "queued_tokens": sched.queued_tokens,
            "offloaded": {
                str(rid): {"blocks": r.n_blocks, "cur_len": r.cur_len}
                for rid, r in sorted(self._offloaded.items())
            },
            "policy": (
                self.policy.stats() if self.policy is not None else None
            ),
            "compile_stats": self.compile_stats(),
            "flight_recorder": (
                None if self._flight is None else {
                    "capacity": self._flight.capacity,
                    "finished_resident": len(self._flight),
                    "in_flight": len(self._flight_live),
                }
            ),
        }
        if self.paged:
            out["blocks_total"] = self.num_blocks
            out["blocks_free"] = self.scheduler.allocator.free_count
            idx = sched.prefix_index
            out["prefix_index"] = (
                idx.stats() if idx is not None else None
            )
        elif sched.prefix_cache is not None:
            out["prefix_cache"] = sched.prefix_cache.stats()
        return out

    def release_telemetry(self) -> None:
        """Retire this engine's labeled series — its own, its
        scheduler's, and its prefix cache's — from the process
        registry. Hosts that construct engines in a loop (the bench's
        alternating rounds, per-request test engines) call this when an
        engine is done so scrape output doesn't accumulate dead
        incarnations; never called implicitly, because scraping a
        finished engine's counters is a supported shape. Object-held
        views (``total_generated`` etc.) keep working."""
        telemetry.remove_series(engine=self.telemetry_label)
        self.scheduler.release_telemetry()

    def compile_stats(self) -> dict:
        """Compiled-program counts (the compile-count introspection
        hook): after warmup ``decode_compiles`` must stay at 1 for the
        server's whole life, and ``prefill_compiles`` is bounded by the
        bucket ladder."""

        def n(f):
            try:
                return int(f._cache_size())
            except Exception:  # pragma: no cover - jax-version drift
                return -1

        if self.paged:
            return {
                # paged closed set: one decode per table bucket, one
                # chunk per (width, table bucket), gather/scatter per
                # bucket touched by preemption
                "decode_compiles": n(self._paged_decode_jit),
                "prefill_compiles": 0,
                "chunk_prefill_compiles": n(self._paged_chunk_jit),
                "copy_compiles": 0,  # prefix hits are table splices
                "offload_compiles": n(self._gather_jit),
                "resume_compiles": n(self._scatter_jit),
                "verify_compiles": (
                    n(self._verify_jit) if self.speculative else 0
                ),
                "sp_prefill_compiles": (
                    n(self._sp_jit) if self._sp_jit is not None else 0
                ),
                "score_compiles": n(self._score_jit),
                "buckets": tuple(self.scheduler.buckets),
                "table_buckets": tuple(self._tbuckets),
                "prefill_chunk": self.prefill_chunk,
                "block_size": self.block_size,
                "num_blocks": self.num_blocks,
                "spec_k": self.spec_k,
                "attention": self.attention,
                "kv_dtype": self.kv_dtype,
            }
        return {
            "decode_compiles": n(self._decode_jit),
            "prefill_compiles": n(self._prefill_jit),
            "chunk_prefill_compiles": n(self._chunk_jit),
            "copy_compiles": n(self._copy_jit),
            "verify_compiles": (
                n(self._verify_jit) if self.speculative else 0
            ),
            "score_compiles": n(self._score_jit),
            "buckets": tuple(self.scheduler.buckets),
            # flash block-span reads compile per touched span bucket
            # (closed ladder); naive never leaves the maxlen span, so
            # its decode stays the seed's single program
            "span_buckets": tuple(self._sbuckets),
            "prefill_chunk": self.prefill_chunk,
            "spec_k": self.spec_k,
            "attention": self.attention,
            "kv_dtype": self.kv_dtype,
        }

    def _tenant_stats(self) -> dict:
        """Per-tenant queue depth, admitted/rejected counts, token
        totals, and SLO attainment — registry-backed (ISSUE 10
        satellite). Empty without a policy (no tenants exist)."""
        if self.policy is None:
            return {}
        out = {}
        for t in self.policy.tenant_names:
            met = int(self._tenant_child(self._mf_slo_met, t).value)
            missed = int(
                self._tenant_child(self._mf_slo_missed, t).value
            )
            out[t] = {
                "queue_depth": self.scheduler.waiting_count(t),
                "admitted": int(
                    self._tenant_child(self._mf_tenant_admitted, t).value
                ),
                "rejected": int(
                    self._tenant_child(self._mf_tenant_rejected, t).value
                ),
                "tokens": int(
                    self._tenant_child(self._mf_tenant_tokens, t).value
                ),
                "slo_met": met,
                "slo_missed": missed,
                "slo_attainment": (
                    met / (met + missed) if met + missed else None
                ),
            }
        return out

    @staticmethod
    def _percentiles(xs) -> dict:
        """``{p50, p99, n}`` summary (seconds) of a latency sample."""
        if not xs:
            return {"p50": None, "p99": None, "n": 0}
        return {
            "p50": float(np.percentile(xs, 50)),
            "p99": float(np.percentile(xs, 99)),
            "n": len(xs),
        }

    def stats(self) -> dict:
        """Serving counters for the bench: aggregate generated tokens,
        decode steps, mean slot occupancy, per-request whole-request
        latencies, TTFT (submit→first token) and inter-token arrival
        percentiles of finished requests (ISSUE 4 — the chunked-prefill
        and prefix-reuse wins read straight off these counters), plus
        prefix-cache hit/eviction counters when the cache is on."""
        finished = list(self.finished.values())
        lat = [
            r.finish_time - r.submit_time
            for r in finished
            if r.finish_time is not None and r.submit_time is not None
        ]
        ttfts = [r.ttft for r in finished if r.ttft is not None]
        itls = [d for r in finished for d in r.inter_token_times]
        # decode-only tok/s (ISSUE 8 satellite): per-token speed with
        # TTFT excluded — from each finished request's first-to-last
        # token arrival window, the same token_times the percentiles
        # already read. This is the figure speculation moves; aggregate
        # tok/s confounds it with batching and admission effects.
        d_toks = sum(
            len(r.token_times) - 1
            for r in finished if len(r.token_times) > 1
        )
        d_secs = sum(
            r.token_times[-1] - r.token_times[0]
            for r in finished if len(r.token_times) > 1
        )
        drafted = int(self._m_spec_drafted.value)
        accepted = int(self._m_spec_accepted.value)
        out = {
            "total_generated": self.total_generated,
            # which attention kernel the programs run (ISSUE 11) —
            # same truth the elephas_serving_attn_kernel info gauge
            # labels, so dashboards and stats() can never disagree
            "attention": self.attention,
            "decode_steps": self.scheduler._steps,
            "occupancy": self.scheduler.occupancy,
            "latencies": lat,
            "finished": self.finished_count,
            "finished_evicted": self.finished_evicted,
            "num_slots": self.num_slots,
            "ttft_s": self._percentiles(ttfts),
            "inter_token_s": self._percentiles(itls),
            # ISSUE 7 satellite: gauge/counter-backed so stats() and a
            # /metrics scrape can never drift (one store, two views)
            "queue_depth": int(self.scheduler._m_waiting.value),
            "preemptions": int(self._m_preemptions.value),
            "resumes": int(self._m_resumes.value),
            "rejected": int(self._m_rejected.value),
            "decode_tok_s": (d_toks / d_secs) if d_secs > 0 else None,
            # speculative decoding (ISSUE 8): registry-backed like the
            # paged counters — stats() and a /metrics scrape read the
            # SAME series; the acceptance rate is derived at read time
            "spec_draft_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_acceptance_rate": (
                accepted / drafted if drafted else None
            ),
            "spec_verify_rounds": int(self._m_spec_rounds.value),
            "spec_throttled": int(self._m_spec_throttled.value),
            # SLO scheduling (ISSUE 10): same one-store contract — the
            # per-tenant section reads the registry children and the
            # live scheduler queue, so stats() and a /metrics scrape
            # can never drift
            "admission_rejected": int(self._m_admission_rejected.value),
            "tenants": self._tenant_stats(),
            # lifecycle control (ISSUE 14): registry-backed like the
            # rest — stats() and a /metrics scrape read the same series
            "cancelled": int(self._m_cancelled.value),
            "migrated_out": int(self._m_migrated_out.value),
            "migrated_in": int(self._m_migrated_in.value),
            # quantized KV (ISSUE 19): the stored dtype plus the
            # counted wire/offload byte totals the bench's compression
            # gate reads — registry-backed, one store, two views
            "kv_dtype": self.kv_dtype,
            "kv_quant_offload_bytes": int(self._m_offload_bytes.value),
            "kv_quant_export_bytes": int(self._m_export_bytes.value),
            "score_requests": int(self._m_score_requests.value),
            # continuous deployment (ISSUE 20): the generation this
            # engine serves — same truth the weight_version gauge holds
            "weight_version": self.weight_version,
        }
        if self.policy is not None:
            out["policy"] = self.policy.stats()
        if self.paged:
            alloc = self.scheduler.allocator
            out["blocks_total"] = self.num_blocks
            out["blocks_free"] = alloc.free_count
            out["offloaded_blocks"] = int(self._m_offload_blocks.value)
            idx = self.scheduler.prefix_index
            out["prefix_blocks_shared"] = (
                idx.shared_blocks if idx is not None else 0
            )
            if idx is not None:
                out["prefix_cache"] = idx.stats()
        if self.scheduler.prefix_cache is not None:
            out["prefix_cache"] = self.scheduler.prefix_cache.stats()
        return out
