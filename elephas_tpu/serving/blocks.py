"""Refcounted block allocator for the paged KV arena (ISSUE 7).

Pure host-side bookkeeping, no jax anywhere — the paged sibling of the
scheduler's slot free list. The allocator owns which pool blocks are
leased and by how many holders:

- an active request's block table holds one reference per block;
- a prefix-index entry (:class:`~elephas_tpu.serving.prefix_cache.\
PagedPrefixIndex`) holds one reference per indexed full-prompt block;
- a prefix HIT splices the entry's blocks into the new table with one
  more reference each — copy-free sharing, safe because a sharer only
  ever writes at positions at/after its shared full-block boundary
  (so shared blocks are effectively immutable; no copy-on-write
  needed).

A block returns to the free list only when its last reference drops.
Everything is deterministic for the SPMD gang contract: the free list
stays sorted ascending, allocation takes lowest ids first, and no
wall-clock is consulted anywhere. The optional ``free_gauge`` is
report-only telemetry (a registry gauge mirroring ``free_count`` for
``stats()`` / ``/metrics`` no-drift) — it never drives control flow.
"""

from __future__ import annotations


class BlockAllocator:
    """Deterministic refcounted free-list over ``num_blocks`` pool
    blocks of ``block_size`` positions each."""

    def __init__(self, num_blocks: int, block_size: int,
                 free_gauge=None):
        if int(num_blocks) < 1:
            raise ValueError(f"num_blocks={num_blocks} < 1")
        if int(block_size) < 1:
            raise ValueError(f"block_size={block_size} < 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: list[int] = list(range(self.num_blocks))
        self._refs: dict[int, int] = {}
        self._gauge = free_gauge
        self._set_gauge()

    def _set_gauge(self) -> None:
        if self._gauge is not None:
            self._gauge.set(len(self._free))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def leased_count(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Lease ``n`` fresh blocks (one reference each), lowest ids
        first — or None when the free list is short (the caller evicts
        prefix entries / preempts / waits; a partial grant would leak
        determinism into retry paths)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        blocks, self._free = self._free[:n], self._free[n:]
        for b in blocks:
            self._refs[b] = 1
        self._set_gauge()
        return blocks

    def ref(self, blocks) -> None:
        """Take one more reference on each (already-leased) block."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"ref() on unleased block {b}")
            self._refs[b] += 1

    def deref(self, blocks) -> list[int]:
        """Drop one reference per block; blocks reaching zero return
        to the free list. Returns the freed ids (sorted)."""
        freed = []
        for b in blocks:
            refs = self._refs.get(b)
            if refs is None:
                raise ValueError(f"deref() on unleased block {b}")
            if refs == 1:
                del self._refs[b]
                freed.append(b)
            else:
                self._refs[b] = refs - 1
        if freed:
            freed.sort()
            self._free = sorted(self._free + freed)
            self._set_gauge()
        return freed

    def ref_count(self, block: int) -> int:
        return self._refs.get(int(block), 0)
