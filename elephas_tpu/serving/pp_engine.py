"""Pipeline-parallel serving: continuous batching over a PP×TP mesh
(ISSUE 15).

Every serving path before this module tops out at one TP/DP chip
group: the whole model's weights must fit the group, so model DEPTH is
the one scaling axis the engine cannot buy hardware for. This module
runs the continuous-batching loop over the pre-seed pipeline ring
(:mod:`elephas_tpu.parallel.pipeline_runner`'s stage planner and the
``ppermute`` ring :mod:`elephas_tpu.ops.pipeline` certified for
training and one-shot ring decode): the causal LM depth-shards into
``S`` stages over a ``('stages',)`` mesh axis (width-sharding each
stage over a trailing ``('model',)`` axis under PP×TP), each stage
holds ONLY its layers' weights and its OWN paged KV pool, and decode
runs as **microbatched waves that fill the pipeline bubble**
(GPipe-style microbatching, Huang et al. 2019, composed with
iteration-level continuous batching, Orca, Yu et al. 2022):

- the slot arena partitions STATICALLY into ``S`` waves of
  ``wave_slots`` slots each (slot ``i`` belongs to wave
  ``i // wave_slots``);
- one decode **window** is a single compiled dispatch of
  ``S·k + S − 1`` ring ticks (``k = steps_per_wave``): at tick ``t``
  stage ``s`` decodes wave ``(t − s) mod S``, so while wave ``w``
  crosses stage ``s``, wave ``w+1`` occupies stage ``s−1`` — in steady
  state every stage is busy every tick and the window emits ``S·k``
  wave-tokens for ``S·k + S − 1`` ticks (bubble fraction
  ``(S−1)/(S·k+S−1)``, amortized by ``k``);
- the sampled token of wave ``w`` rides the ring's wrap edge (stage
  ``S−1`` → stage ``0``) and seeds the SAME wave's next position one
  tick later — with ``waves == stages`` the hand-off is exact, so the
  token loop closes entirely on device and the host syncs once per
  window (admission, EOS/budget reclaim, mid-flight arrivals);
- prefill is the same ring with a chunk per wave: one dispatch walks
  an admission wave's (bucket-padded) prompts through all stages,
  landing each stage's K/V in its own pool and sampling first tokens
  on the last stage;
- **bubble-filling chunked prefill** (ISSUE 16, ``bubble_fill=True``):
  at tick ``t`` stage ``s`` is idle whenever wave ``(t − s) mod S``
  has no decode work — an admission landing in such an EMPTY wave
  becomes a *filler*: its prompt prefills chunk-by-chunk
  (``bubble_chunk`` positions per ring round) through exactly those
  idle ticks of the SAME compiled decode window, Sarathi-style
  (Agrawal et al., 2024) piggybacking on a pipeline ring, so a
  mid-flight long-prompt arrival reaches its first token without a
  standalone prefill dispatch between windows;
- **cross-stage prefix sharing** (``prefix_cache=True``): the
  scheduler's :class:`PagedPrefixIndex` spans the per-stage pools for
  free — ONE allocator leases each block id on EVERY stage, so a
  prefix-hit splice makes the shared prompt's K/V resident on all
  stages at once and both the prefill ring and the fill path start at
  the shared offset (a shared system prompt pays one cold prefill
  fleet-wide).

Kept invariants (the standing serving contracts):

- **no wall clock near ordering** — the schedule is a pure function of
  the submission sequence; gang processes derive identical waves;
- **closed compile set** — programs key on (chunk-width bucket ×
  table bucket); the decode ring compiles once per table bucket;
- **temp-0 token-exactness** vs one-shot ``generate()`` (the stage
  replay reuses the paged arena's attention math; under TP the
  head-split psum reassociates floats exactly like the GSPMD TP
  serving path — argmax parity on trained models, the same tested
  contract);
- **telemetry observes, never drives** — per-window bubble-fraction
  and per-wave occupancy gauges plus ``serve.wave`` spans ride along,
  and nothing reads them back.

Per-stage KV: every stage's pool is ``[L_s, num_blocks, block_size,
H, Dh]`` per K/V (``L_s = num_layers / num_stages`` — the planner
refuses an uneven split), stacked ``[S, L_s, ...]`` and sharded over
the stage axis; ONE block allocator leases block *ids* per slot and
every stage stores its layers' rows at those ids in its own pool, so
the block tables replicate and preemption offload gathers **per
stage** (the offload record is the stage-stacked dense rows).

Not in this engine (serve through :class:`~elephas_tpu.serving.\
engine.InferenceEngine` for these): speculative decoding, SLO
policies, SP prefill, migration export/import. Preemption
offload/resume IS here — pool pressure is where PP serving lives —
and so are the paged prefix cache, bubble-fill chunked prefill, and
``cancel()`` (ISSUE 16).
"""

from __future__ import annotations

import logging
import time

import numpy as np

from elephas_tpu import telemetry
from elephas_tpu.serving.blocks import BlockAllocator
from elephas_tpu.serving.paged_kv import (
    blocks_for,
    table_bucket_for,
    table_buckets,
)
from elephas_tpu.serving.scheduler import (
    Request,
    Scheduler,
    default_buckets,
)

logger = logging.getLogger(__name__)


class _StageOffload:
    """Host K/V of a preempted request, PER STAGE: dense block rows
    ``[S, L_s, n_blocks, block_size, H, Dh]`` for K and V plus the
    cursor needed for a bit-exact resume."""

    __slots__ = ("k_rows", "v_rows", "n_blocks", "cur_len")

    def __init__(self, k_rows, v_rows, n_blocks, cur_len):
        self.k_rows = k_rows
        self.v_rows = v_rows
        self.n_blocks = int(n_blocks)
        self.cur_len = int(cur_len)


def _replay_nodes(nodes, in_kt, out_kt, x, handler):
    """Run a stage's node program on ``x`` — the per-stage sibling of
    :func:`~elephas_tpu.serving.kv_cache._graph_replay`: same handler
    contract, but over a node SUBSET with an explicit boundary input
    instead of the whole model's ``_run_through_graph``."""
    from keras import tree as ktree

    tensors = {id(in_kt): x}
    for node in nodes:
        args, kwargs = node.arguments.fill_in(tensors)
        out = handler(node.operation)(*args, **kwargs)
        for kt, val in zip(node.outputs, ktree.flatten(out)):
            tensors[id(kt)] = val
    return tensors[id(out_kt)]


class PPEngine:
    """Continuous-batching serving engine over a pipeline-parallel
    (optionally ×TP) mesh.

    ``num_stages`` depth stages over ``('stages',)`` (one device group
    per stage; ``model_parallel`` width-shards attention heads over a
    trailing ``('model',)`` axis — ``num_heads % model_parallel`` must
    be 0). ``wave_slots`` slots per wave, ``num_stages`` waves (the
    wave count equals the stage count so the ring's wrap edge hands a
    wave's sampled token straight back to stage 0), so the arena holds
    ``num_stages · wave_slots`` slots. ``steps_per_wave`` tokens per
    wave per decode window (the PP analogue of ``steps_per_sync`` —
    larger windows amortize the ``S−1``-tick pipeline fill).

    The KV storage is always paged (``block_size``/``num_blocks``
    as in ``InferenceEngine(paged=True)``; ``num_blocks`` counts
    blocks PER STAGE — every stage's pool has the same geometry).
    ``preemption=True`` arms priority preempt → per-stage host
    offload → bit-exact resume. Submission/driving API mirrors
    ``InferenceEngine``: :meth:`submit`, :meth:`step`,
    :meth:`stream`, :meth:`run`, :meth:`cancel`, :meth:`stats`.

    ``bubble_fill=True`` arms bubble-filling chunked prefill (ISSUE
    16): an admission whose wave-aware slot lands in a wave with no
    decode-active occupant (while another wave decodes) prefills
    ``bubble_chunk`` prompt positions per ring round through that
    wave's otherwise-idle decode-window ticks; ``bubble_budget`` caps
    concurrent fill slots. Off by default: the combined window
    program carries a per-wave fill/decode branch, so the default
    engine keeps PR 15's program byte-for-byte. At temp 0 filled and
    unfilled schedules are token-exact (argmax reads no PRNG); at
    temp>0 they sample DIFFERENT streams — the window consumes one
    key split per tick either way, but fill changes WHICH window
    serves a token, hence which split it reads.

    ``prefix_cache=True`` turns on the scheduler's refcounted
    :class:`PagedPrefixIndex` over the per-stage pools: one block id
    is resident on every stage, so a prefix hit splices shared
    blocks fleet-wide and prefill (standalone or fill) starts at the
    shared offset. ``prefix_min_reuse`` floors the match depth.

    Gang contract: like every serving surface, all gang processes must
    construct the engine identically and submit the identical request
    sequence; the schedule contains no wall clock, so all derive the
    same waves and read the same tokens.
    """

    def __init__(self, model, num_stages: int = 2, wave_slots: int = 2,
                 mesh=None, model_parallel: int = 1,
                 block_size: int | None = None,
                 num_blocks: int | None = None,
                 steps_per_wave: int = 4,
                 top_k: int | None = None, top_p: float | None = None,
                 seed: int = 0, buckets=None,
                 preemption: bool = False,
                 attention: str = "flash",
                 bubble_fill: bool = False,
                 bubble_chunk: int | None = None,
                 bubble_budget: int | None = None,
                 prefix_cache: bool = False,
                 prefix_min_reuse: int = 1):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.models.transformer import (
            validate_token_decode_model,
        )
        from elephas_tpu.ops.pipeline import pipeline_mesh
        from elephas_tpu.parallel.pipeline_runner import (
            plan_serving_stages,
        )

        flash_layers, _stock, _gqa = validate_token_decode_model(
            model,
            what="the PP serving engine",
            hint="use InferenceEngine on a TP/DP mesh",
            allow_stock=False,
        )
        self.model = model
        self.maxlen = int(model.inputs[0].shape[1])
        self.vocab = int(model.outputs[0].shape[-1])
        self.top_k = top_k
        self.top_p = top_p
        if top_k is not None and not 0 < int(top_k) <= self.vocab:
            raise ValueError(
                f"top_k={top_k} outside (0, vocab={self.vocab}]"
            )
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p={top_p} outside (0, 1]")
        if attention not in ("flash", "naive"):
            raise ValueError(
                f"attention must be 'flash' or 'naive', got "
                f"{attention!r}"
            )
        self.attention = attention

        S = int(num_stages)
        mp = max(1, int(model_parallel))
        self.num_stages = S
        self.model_parallel = mp
        self.plan = plan_serving_stages(model, S)
        geoms = {
            (int(l.num_heads), int(l.head_dim)) for l in flash_layers
        }
        if len(geoms) != 1:
            raise ValueError(
                f"PP serving stacks per-stage KV pools into one "
                f"buffer, which needs uniform attention geometry — "
                f"model mixes {sorted(geoms)}"
            )
        (self.num_heads, self.head_dim), = geoms
        if mp > 1 and self.num_heads % mp:
            raise ValueError(
                f"model_parallel={mp} needs num_heads "
                f"({self.num_heads}) divisible by it (heads split "
                f"over the model axis)"
            )
        self.layers_per_stage = len(self.plan.flash[0])

        if mesh is None:
            mesh = pipeline_mesh(S, model_parallel=mp)
        if mesh.shape.get("stages", 0) != S:
            raise ValueError(
                f"mesh axis 'stages' has size "
                f"{mesh.shape.get('stages', 0)}, need {S}"
            )
        if mesh.shape.get("model", 1) != mp:
            raise ValueError(
                f"mesh axis 'model' has size "
                f"{mesh.shape.get('model', 1)} but "
                f"model_parallel={mp}"
            )
        self.mesh = mesh

        ws = int(wave_slots)
        if ws < 1:
            raise ValueError(f"wave_slots={wave_slots} < 1")
        self.wave_slots = ws
        self.num_slots = S * ws
        k = int(steps_per_wave)
        if k < 1:
            raise ValueError(f"steps_per_wave={steps_per_wave} < 1")
        self.steps_per_wave = k

        bs = 16 if block_size is None else int(block_size)
        if not 0 < bs <= self.maxlen:
            raise ValueError(
                f"block_size={bs} outside (0, maxlen={self.maxlen}]"
            )
        self.block_size = bs
        self.max_blocks_per_slot = blocks_for(self.maxlen, bs)
        nb = (
            int(num_blocks) if num_blocks is not None
            else self.num_slots * self.max_blocks_per_slot
        )
        if nb < 1:
            raise ValueError(f"num_blocks={nb} < 1")
        self.num_blocks = nb
        self._tbuckets = table_buckets(self.max_blocks_per_slot)
        self.preemption = bool(preemption)

        # bubble-fill knobs (ISSUE 16): the chunk width is the fill
        # path's per-round position count — it sizes the window ring
        # buffer (ws·C·D_max), so the OFF engine pins C=1 and keeps
        # PR 15's program byte-for-byte
        self.bubble_fill = bool(bubble_fill)
        C = bs if bubble_chunk is None else int(bubble_chunk)
        if not 0 < C <= self.maxlen:
            raise ValueError(
                f"bubble_chunk={C} outside (0, maxlen={self.maxlen}]"
            )
        self.bubble_chunk = C
        self._C = C if self.bubble_fill else 1
        if bubble_budget is not None and int(bubble_budget) < 1:
            raise ValueError(f"bubble_budget={bubble_budget} < 1")
        self.bubble_budget = (
            None if bubble_budget is None else int(bubble_budget)
        )

        # -- telemetry captured at construction (the standing serving
        # contract: null-built engines stay inert for life) -----------
        treg = telemetry.registry()
        self._telemetry_registry = treg
        self._tracer = telemetry.tracer()
        eid = telemetry.instance_label()
        self.telemetry_label = eid

        def _c(name, help_):
            return treg.counter(
                name, help_, labels=("engine",)
            ).labels(engine=eid)

        # shared serving families (same name+help as InferenceEngine's
        # so the catalog stays one family per concept; this engine is
        # just another engine= child)
        self._m_tokens = _c(
            "elephas_serving_tokens_generated_total",
            "Generated tokens emitted by the serving engine",
        )
        self._m_finished = _c(
            "elephas_serving_requests_finished_total",
            "Requests that completed (EOS or token budget)",
        )
        self._m_decode_windows = _c(
            "elephas_serving_decode_windows_total",
            "Arena-wide decode window dispatches",
        )
        self._m_preemptions = _c(
            "elephas_serving_preemptions_total",
            "Requests preempted (blocks offloaded to host) so a "
            "higher-priority arrival could admit",
        )
        self._m_resumes = _c(
            "elephas_serving_resumes_total",
            "Preempted requests restored from host offload",
        )
        self._m_offload_blocks = _c(
            "elephas_serving_offloaded_blocks_total",
            "KV pool blocks swapped to host memory by preemption",
        )
        self._m_rejected = _c(
            "elephas_serving_rejected_total",
            "Requests rejected at submit because prompt + "
            "max_new_tokens can never fit the block pool",
        )
        self._m_cancelled = _c(
            "elephas_serving_cancelled_total",
            "In-flight requests cancelled before completion "
            "(slot/blocks reclaimed; gateway client disconnects land "
            "here)",
        )
        # bubble-fill + cross-stage prefix telemetry (ISSUE 16) —
        # report-only like every serving series
        self._m_fill_tokens = _c(
            "elephas_pp_fill_tokens_total",
            "Prompt tokens prefilled through idle decode-window ring "
            "ticks (bubble fill)",
        )
        self._m_fill_rounds = _c(
            "elephas_pp_fill_rounds_total",
            "Fill-wave ring rounds carried inside decode windows "
            "(idle ticks that did prefill work instead)",
        )
        self._m_prefix_shared = _c(
            "elephas_pp_prefix_shared_tokens_total",
            "Prompt tokens served by cross-stage prefix-block "
            "splices (shared block ids resident on every stage)",
        )
        self._m_ttft = treg.histogram(
            "elephas_serving_ttft_seconds",
            "Submit-to-first-token latency of served requests",
            labels=("engine",),
        ).labels(engine=eid)
        self._m_itl = treg.histogram(
            "elephas_serving_inter_token_seconds",
            "Arrival gap between consecutive tokens of one request",
            labels=("engine",),
        ).labels(engine=eid)
        treg.gauge(
            "elephas_serving_slots", "KV-cache slots in the arena",
            labels=("engine",),
        ).labels(engine=eid).set(self.num_slots)
        treg.gauge(
            "elephas_serving_kv_blocks",
            "KV pool blocks in the paged arena",
            labels=("engine",),
        ).labels(engine=eid).set(self.num_blocks)
        # PP-specific report-only series (ISSUE 15): the pipeline-fill
        # overhead of the last decode window — scheduled stage-ticks
        # that carried no wave work (ramp/drain plus EMPTY waves) over
        # all scheduled stage-ticks — and per-wave live-slot occupancy.
        # Report-only by contract: nothing reads these back.
        self._m_bubble = treg.gauge(
            "elephas_pp_bubble_fraction",
            "Pipeline-bubble fraction of the last decode window "
            "(idle stage-ticks / scheduled stage-ticks; ramp + drain "
            "+ empty waves; bubble-filled ticks count as useful)",
            labels=("engine",),
        ).labels(engine=eid)
        self._mf_wave_active = treg.gauge(
            "elephas_pp_wave_active_slots",
            "Live (decoding) slots per pipeline wave at the last "
            "window boundary",
            labels=("engine", "wave"),
        )
        for w in range(S):
            self._mf_wave_active.labels(engine=eid, wave=str(w)).set(0)

        allocator = BlockAllocator(
            self.num_blocks, bs,
            free_gauge=treg.gauge(
                "elephas_serving_blocks_free",
                "Unleased KV pool blocks (paged arena)",
                labels=("engine",),
            ).labels(engine=eid),
        )
        self.scheduler = Scheduler(
            self.num_slots, buckets or default_buckets(self.maxlen),
            allocator=allocator, preemption=preemption,
            wave_slots=ws, prefix_cache=bool(prefix_cache),
            prefix_min_reuse=prefix_min_reuse,
        )
        self._seed = int(seed)
        self.finished: dict[int, Request] = {}
        self._finished_bound = 4096
        self._protected: set[int] = set()
        self._offloaded: dict[int, _StageOffload] = {}
        self._active_host = np.zeros((self.num_slots,), bool)
        self._tables_cache: tuple | None = None
        self._last_bubble = 0.0
        # bubble-fill host state: slot → next prompt offset to fill
        # (in-progress fillers), and slots whose prompt finished
        # filling but whose WAVE still has fillers in flight
        # (whole-wave graduation — see _decode_window)
        self._filling: dict[int, int] = {}
        self._fill_done: set[int] = set()
        # cumulative ring accounting (stage-ticks scheduled vs
        # carrying work, windows AND standalone prefill dispatches) —
        # stats()['bubble_cumulative'], report-only
        self._ticks_sched = 0
        self._ticks_useful = 0
        self._trace_compiles = not telemetry.null_mode()

        # -- stage weights: per-stage (per-rank under TP) {path: value}
        # pytrees raveled into ONE stacked f32 buffer sharded over the
        # stage (× model) axes — the GPipeTrainer storage pattern, so
        # no device ever holds more than its stage's (rank's) share
        self._build_stage_weights()

        # -- per-stage pools: [S, L_s, N, bs, H, Dh] per K/V, stage
        # axis sharded, head axis sharded under TP
        model_ax = "model" if mp > 1 else None
        self._pool_spec = P("stages", None, None, None, model_ax, None)
        self._pool_sh = NamedSharding(mesh, self._pool_spec)
        self._param_spec = (
            P("stages", "model") if mp > 1 else P("stages",)
        )
        self._rep_sh = NamedSharding(mesh, P())
        # per-DEVICE local pool shape (stage axis 1, heads rank-local);
        # the zeros build through a shard_map with the SAME out_specs
        # as the ring programs, so the initial pools carry the
        # identical sharding object shape the ring outputs do — a
        # plain out_shardings= jit produced an equivalent-but-distinct
        # sharding whose first ring dispatch minted a SECOND executable
        # cache entry (found via the closed-compile-set test)
        local_shape = (
            1, self.layers_per_stage, self.num_blocks, bs,
            self.num_heads // mp, self.head_dim,
        )

        def _init_pools():
            from elephas_tpu.parallel.mesh import shard_map_compat

            def per_device():
                z = jnp.zeros(local_shape, jnp.float32)
                return z, jnp.zeros(local_shape, jnp.float32)

            return shard_map_compat(
                per_device, mesh=mesh, in_specs=(),
                out_specs=(self._pool_spec, self._pool_spec),
                check=False,
            )()

        self._pk, self._pv = jax.jit(_init_pools)()

        self._build_programs()
        self._key = self._stage_host(
            np.asarray(jax.random.PRNGKey(self._seed))
        )

    # -- staging helpers ------------------------------------------------

    def _stage_host(self, arr):
        """Host value → device, replicated over the PP mesh
        (gang-safe: every process materializes its own shards)."""
        from elephas_tpu.parallel.mesh import put_global

        return put_global(np.asarray(arr), self._rep_sh)

    def _host(self, leaf) -> np.ndarray:
        from elephas_tpu.parallel.mesh import host_read

        return host_read(leaf, self.mesh)

    # -- weights --------------------------------------------------------

    def _stage_var_value(self, layer, v, rank: int):
        """Rank ``rank``'s storage shard of one variable: FlashMHA
        qkv/proj split Megatron-style (head slices), everything else
        replicated — the serving TP plan (attention is where both the
        FLOPs and the KV live; MLP/LN/embedding run replicated inside
        the stage's model group)."""
        from elephas_tpu.parallel.pipeline_runner import _tp_slice_var

        mp = self.model_parallel
        val = np.asarray(v.value)
        if mp == 1:
            return val
        from elephas_tpu.models.transformer import _flash_mha_layer

        if isinstance(layer, _flash_mha_layer()):
            if v is layer.qkv.kernel:
                return _tp_slice_var(
                    val, ("split_qkv", self.num_heads, self.head_dim),
                    rank, mp,
                )
            if v is layer.proj.kernel:
                return _tp_slice_var(val, ("split", 0), rank, mp)
        return val

    def _stage_weight_dict(self, s: int, rank: int) -> dict:
        """Stage ``s``'s ``{var.path: np value}`` dict for one model
        rank. Dropout layers are identity in the serving replay, so
        their (integer RNG) state never enters the f32 flat buffer."""
        import keras

        out = {}
        for layer in self.plan.layers[s]:
            if isinstance(layer, keras.layers.Dropout):
                continue
            for v in layer.variables:
                if not np.issubdtype(
                    np.dtype(v.dtype), np.floating
                ):
                    raise ValueError(
                        f"PP serving packs stage weights into one f32 "
                        f"buffer; variable {v.path} is {v.dtype}"
                    )
                out[v.path] = self._stage_var_value(
                    layer, v, rank
                ).astype(np.float32)
        return out

    def _build_stage_weights(self) -> None:
        """(Re)build the stacked flat stage-weight buffer from the
        model's current variables — also the :meth:`refresh_weights`
        body."""
        from jax.flatten_util import ravel_pytree

        from elephas_tpu.parallel.mesh import put_global

        S, mp = self.num_stages, self.model_parallel
        flats = []  # [S][mp] np flat vectors
        unravels, sizes = [], []
        for s in range(S):
            rank_flats = []
            for r in range(mp):
                flat, unravel = ravel_pytree(
                    self._stage_weight_dict(s, r)
                )
                rank_flats.append(np.asarray(flat, np.float32))
            flats.append(rank_flats)
            unravels.append(unravel)  # same structure across ranks
            sizes.append(int(rank_flats[0].size))
        self._unravels = tuple(unravels)
        self._p_sizes = tuple(sizes)
        self.P_max = max(sizes)
        if mp > 1:
            stacked = np.stack([
                np.stack([
                    np.pad(f, (0, self.P_max - f.size))
                    for f in rank_flats
                ])
                for rank_flats in flats
            ])  # [S, mp, P_max]
        else:
            stacked = np.stack([
                np.pad(flats[s][0], (0, self.P_max - flats[s][0].size))
                for s in range(S)
            ])  # [S, P_max]
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P("stages", "model") if mp > 1 else P("stages",)
        self._wflat = put_global(
            stacked, NamedSharding(self.mesh, spec)
        )

    def refresh_weights(self) -> None:
        """Re-upload the model's weights after further training (the
        compiled ring programs take them as arguments — no
        recompile). Flushes the prefix index: rows indexed under the
        old weights are stale K/V for the new ones."""
        tracer = getattr(self, "_tracer", None)
        if tracer is not None:
            tracer.emit(
                "serve.refresh_weights", engine=self.telemetry_label,
            )
        self._build_stage_weights()
        sched = getattr(self, "scheduler", None)
        if sched is not None:
            sched.flush_prefix_cache()

    # -- stage branch construction --------------------------------------

    def _make_attn_closure(self, op, li: int, mode: str, ctx):
        """The per-layer attention closure of one stage branch:
        ``mode='decode'`` is one token per wave slot at per-slot
        positions, ``mode='chunk'`` a whole (padded) prompt chunk —
        the per-stage mirrors of ``paged_token_decode_step`` /
        ``paged_chunk_forward``'s local fast path (shard_map bodies
        are manual SPMD, so native gather/scatter is always legal
        here). K/V lands in THIS stage's pool slice at the slot's
        leased block ids; under TP the heads are rank-local and the
        output projection psums over the model axis."""
        import jax
        import jax.numpy as jnp

        from elephas_tpu.models.transformer import (
            _apply_rope,
            _rope_tables,
        )
        from elephas_tpu.ops.flash_serving import (
            flash_span_chunk,
            flash_span_decode,
        )
        from elephas_tpu.serving.kv_cache import (
            _rows_at_position_matrix,
            _rows_at_positions,
        )

        mp = self.model_parallel
        Hl = self.num_heads // mp
        Dh = self.head_dim
        bs = self.block_size
        N = self.num_blocks
        maxlen = self.maxlen
        attention = self.attention
        qkv_path = op.qkv.kernel.path
        proj_path = op.proj.kernel.path
        bias_path = op.proj.bias.path

        def _proj_out(o, w):
            out = o @ w[proj_path]
            if mp > 1:
                out = jax.lax.psum(out, "model")
            return out + w[bias_path]

        if mode == "decode":

            def attn(x, *_a, **_k):
                w, pk, pv, updated = ctx["w"], ctx["pk"], ctx["pv"], \
                    ctx["updated"]
                pos_w, act_w, tab_w = (
                    ctx["pos"], ctx["act"], ctx["tables"]
                )
                lk, lv = pk[li], pv[li]  # [N, bs, Hl, Dh]
                ws_n = x.shape[0]
                T = tab_w.shape[1]
                qkv = x @ w[qkv_path]
                q, kk, vv = jnp.split(
                    qkv.reshape(ws_n, 3, Hl, Dh), 3, axis=1
                )
                q, kk, vv = q[:, 0], kk[:, 0], vv[:, 0]
                if getattr(op, "rope", False):
                    cos_np, sin_np = _rope_tables(maxlen, Dh)
                    cos_t = _rows_at_positions(
                        jnp.asarray(cos_np), pos_w
                    )[:, None, :]
                    sin_t = _rows_at_positions(
                        jnp.asarray(sin_np), pos_w
                    )[:, None, :]
                    q = _apply_rope(q, cos_t, sin_t)
                    kk = _apply_rope(kk, cos_t, sin_t)
                blk_idx = pos_w // bs
                offp = pos_w % bs
                blk = jnp.take_along_axis(
                    tab_w, jnp.clip(blk_idx, 0, T - 1)[:, None],
                    axis=1,
                )[:, 0]
                # cursor overrun past the whole bucket routes to the
                # sentinel (the paged engine's block-0 scribble fix);
                # in-bucket overrun lands on the table's own sentinel
                # padding by construction
                blk = jnp.where(blk_idx < T, blk, N)
                blk_safe = jnp.where(act_w, blk, N)
                lk = lk.at[blk_safe, offp].set(
                    kk.astype(lk.dtype), mode="drop"
                )
                lv = lv.at[blk_safe, offp].set(
                    vv.astype(lv.dtype), mode="drop"
                )
                gk = jnp.take(lk, tab_w, axis=0, mode="clip")
                gk = gk.reshape(ws_n, T * bs, Hl, Dh)
                gv = jnp.take(lv, tab_w, axis=0, mode="clip")
                gv = gv.reshape(ws_n, T * bs, Hl, Dh)
                if attention == "flash":
                    o = flash_span_decode(
                        q, gk, gv, pos_w, scale=Dh**-0.5
                    ).reshape(ws_n, Hl * Dh)
                else:
                    # flash-lint: allow — the selectable naive oracle
                    att = jnp.einsum("bhd,bshd->bhs", q, gk) * (
                        Dh**-0.5
                    )
                    visible = (
                        jnp.arange(T * bs)[None, None, :]
                        <= pos_w[:, None, None]
                    )
                    att = jax.nn.softmax(
                        jnp.where(visible, att, -jnp.inf), axis=-1
                    )
                    # flash-lint: allow — naive oracle att@V
                    o = jnp.einsum(
                        "bhs,bshd->bhd", att, gv
                    ).reshape(ws_n, Hl * Dh)
                updated[li] = (lk, lv)
                return _proj_out(o, w)

            return attn

        def attn(x, *_a, **_k):  # mode == "chunk"
            w, pk, pv, updated = ctx["w"], ctx["pk"], ctx["pv"], \
                ctx["updated"]
            pos_mat, valid, tab_w = (
                ctx["pos_mat"], ctx["valid"], ctx["tables"]
            )
            lk, lv = pk[li], pv[li]
            ws_n, C = x.shape[0], x.shape[1]
            T = tab_w.shape[1]
            qkv = jnp.reshape(
                x @ w[qkv_path], (ws_n, C, 3, Hl, Dh)
            )
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))
            q, kk, vv = qkv[0], qkv[1], qkv[2]  # [ws, Hl, C, Dh]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos = _rows_at_position_matrix(
                    jnp.asarray(cos_np), pos_mat
                )[:, None]
                sin = _rows_at_position_matrix(
                    jnp.asarray(sin_np), pos_mat
                )[:, None]
                q = _apply_rope(q, cos, sin)
                kk = _apply_rope(kk, cos, sin)
            k_rows = jnp.transpose(kk, (0, 2, 1, 3))  # [ws, C, Hl, Dh]
            v_rows = jnp.transpose(vv, (0, 2, 1, 3))
            blk_idx = pos_mat // bs
            off_mat = pos_mat % bs
            blk_mat = jnp.take_along_axis(
                tab_w, jnp.clip(blk_idx, 0, T - 1), axis=1
            )
            blk_mat = jnp.where(blk_idx < T, blk_mat, N)
            blk_safe = jnp.where(valid, blk_mat, N)
            lk = lk.at[blk_safe, off_mat].set(
                k_rows.astype(lk.dtype), mode="drop"
            )
            lv = lv.at[blk_safe, off_mat].set(
                v_rows.astype(lv.dtype), mode="drop"
            )
            gk = jnp.take(lk, tab_w, axis=0, mode="clip")
            gk = gk.reshape(ws_n, T * bs, Hl, Dh)
            gv = jnp.take(lv, tab_w, axis=0, mode="clip")
            gv = gv.reshape(ws_n, T * bs, Hl, Dh)
            if attention == "flash":
                o = flash_span_chunk(
                    q, gk, gv, pos_mat, scale=Dh**-0.5
                )
            else:
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum(
                    "bhcd,bshd->bhcs", q, gk
                ) * (Dh**-0.5)
                visible = (
                    jnp.arange(T * bs)[None, None, None, :]
                    <= pos_mat[:, None, :, None]
                )
                att = jax.nn.softmax(
                    jnp.where(visible, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum("bhcs,bshd->bhcd", att, gv)
            o = jnp.reshape(
                jnp.transpose(o, (0, 2, 1, 3)), (ws_n, C, Hl * Dh)
            )
            updated[li] = (lk, lv)
            return _proj_out(o, w)

        return attn

    def _make_stage_handler(self, s: int, mode: str, ctx):
        """The node-op handler of stage ``s``'s replay — FlashMHA
        routes to the paged attention closure, Dropout is identity,
        every other op runs stateless on the stage's unraveled
        weights, with concrete graph constants (positional tables)
        re-sliced to the wave's positions."""
        import keras

        from elephas_tpu.models.transformer import _flash_mha_layer
        from elephas_tpu.serving.kv_cache import (
            _slice_seq_at_position_matrix,
            _slice_seq_at_positions,
        )

        FlashMHA = _flash_mha_layer()
        flash_idx = {
            id(l): i for i, l in enumerate(self.plan.flash[s])
        }
        maxlen = self.maxlen

        def slice_fn(a):
            if mode == "decode":
                return _slice_seq_at_positions(a, ctx["pos"], maxlen)
            return _slice_seq_at_position_matrix(
                a, ctx["pos_mat"], maxlen
            )

        def handler(op):
            if isinstance(op, FlashMHA):
                return self._make_attn_closure(
                    op, flash_idx[id(op)], mode, ctx
                )
            if isinstance(op, keras.layers.Dropout):
                return lambda x, *a, **k: x
            if isinstance(op, keras.Layer) and op.variables:
                def stateless(*args, _op=op, **kwargs):
                    if kwargs.get("training"):
                        kwargs["training"] = False
                    args = [slice_fn(a) for a in args]
                    w = ctx["w"]
                    tv = [w[v.path] for v in _op.trainable_variables]
                    ntv = [
                        w[v.path]
                        for v in _op.non_trainable_variables
                    ]
                    out, _ = _op.stateless_call(tv, ntv, *args, **kwargs)
                    return out

                return stateless

            def weightless(*args, _op=op, **kwargs):
                args = [slice_fn(a) for a in args]
                kwargs = {
                    kk: slice_fn(vv) for kk, vv in kwargs.items()
                }
                return _op(*args, **kwargs)

            return weightless

        return handler

    # -- compiled ring programs -----------------------------------------

    def _build_programs(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from elephas_tpu.parallel.mesh import shard_map_compat
        from elephas_tpu.serving.engine import _sample_dynamic

        S, ws, k = self.num_stages, self.wave_slots, self.steps_per_wave
        num_slots, maxlen = self.num_slots, self.maxlen
        mesh = self.mesh
        mp = self.model_parallel
        top_k, top_p = self.top_k, self.top_p
        plan = self.plan
        unravels, p_sizes = self._unravels, self._p_sizes
        # the ring buffer carries per-position hidden rows between
        # stages and sampled tokens on the wrap edge; logits never
        # cross (sampling happens ON the last stage), so the buffer is
        # sized by the widest hidden boundary, not the vocab. With
        # bubble-fill armed a fill round moves ws·C hidden rows per
        # tick (C = bubble_chunk); the OFF engine's C is pinned to 1,
        # so its window buffer — and whole program — stays PR 15's
        # byte-for-byte
        D_max = plan.max_boundary_dim
        enable_fill = self.bubble_fill
        Cf = self._C
        B_dec = ws * Cf * D_max
        param_spec = self._param_spec
        pool_spec = self._pool_spec

        def make_decode_branch(s: int):
            nodes, in_kt, out_kt = plan.programs[s]
            first, last = s == 0, s == S - 1
            D_in = None if first else plan.boundary_dims[s - 1]
            unravel, p_size = unravels[s], p_sizes[s]

            def branch(p, tok_in, recv, pk, pv, pos_w, act_w,
                       temps_w, tab_w, sub):
                ctx = {
                    "w": unravel(p[:p_size]),
                    "pk": pk, "pv": pv, "updated": {},
                    "pos": pos_w, "act": act_w, "tables": tab_w,
                }
                handler = self._make_stage_handler(s, "decode", ctx)
                x = tok_in if first else (
                    recv[: ws * D_in].reshape(ws, D_in)
                )
                out = _replay_nodes(nodes, in_kt, out_kt, x, handler)
                for li, (nk, nv) in sorted(ctx["updated"].items()):
                    pk = pk.at[li].set(nk)
                    pv = pv.at[li].set(nv)
                if last:
                    toks = _sample_dynamic(
                        out, sub, temps_w, top_k, top_p
                    )
                    flat = toks.astype(jnp.float32)
                else:
                    flat = out.reshape(-1)
                return (
                    jnp.pad(flat, (0, B_dec - flat.size)), pk, pv,
                )

            return branch

        def make_fill_branch(s: int):
            # the window-resident sibling of make_chunk_branch: one
            # Cf-wide chunk of a filler wave's prompts per ring round,
            # positions (fill offset + round·Cf) + arange(Cf), valid
            # while inside the prompt; the LAST valid position samples
            # the first token exactly like the prefill ring's at_end
            # row, and it rides the same outputs[(round, slot)] write
            nodes, in_kt, out_kt = plan.programs[s]
            first, last = s == 0, s == S - 1
            D_in = None if first else plan.boundary_dims[s - 1]
            unravel, p_size = unravels[s], p_sizes[s]

            def branch(p, rows, recv, pk, pv, offs_w, act_w,
                       p_lens_w, temps_w, tab_w, sub):
                pos_mat = offs_w[:, None] + jnp.arange(Cf)[None, :]
                valid = act_w[:, None] & (
                    pos_mat < p_lens_w[:, None]
                )
                ctx = {
                    "w": unravel(p[:p_size]),
                    "pk": pk, "pv": pv, "updated": {},
                    "pos_mat": pos_mat, "valid": valid,
                    "tables": tab_w,
                }
                handler = self._make_stage_handler(s, "chunk", ctx)
                x = rows if first else (
                    recv[: ws * Cf * D_in].reshape(ws, Cf, D_in)
                )
                out = _replay_nodes(nodes, in_kt, out_kt, x, handler)
                for li, (nk, nv) in sorted(ctx["updated"].items()):
                    pk = pk.at[li].set(nk)
                    pv = pv.at[li].set(nv)
                if last:
                    at_end = (
                        valid & (pos_mat == (p_lens_w - 1)[:, None])
                    ).astype(out.dtype)
                    row = jnp.einsum("wc,wcv->wv", at_end, out)
                    firsts = _sample_dynamic(
                        row, sub, temps_w, top_k, top_p
                    )
                    flat = firsts.astype(jnp.float32)
                else:
                    flat = out.reshape(-1)
                return (
                    jnp.pad(flat, (0, B_dec - flat.size)), pk, pv,
                )

            return branch

        def make_combined_branch(s: int):
            dec = make_decode_branch(s)
            fil = make_fill_branch(s)

            def branch(p, tok_in, recv, pk, pv, pos_w, act_w,
                       temps_w, tab_w, sub, fw, f_rows, f_offs_w,
                       f_plens_w):
                # waves are pure (all-fill or all-decode — the
                # scheduler and _demote_stranded enforce it), so one
                # cond per (stage, tick) picks the wave's mode; the
                # decode side is the PR 15 branch untouched
                return jax.lax.cond(
                    fw,
                    lambda: fil(
                        p, f_rows, recv, pk, pv, f_offs_w, act_w,
                        f_plens_w, temps_w, tab_w, sub,
                    ),
                    lambda: dec(
                        p, tok_in, recv, pk, pv, pos_w, act_w,
                        temps_w, tab_w, sub,
                    ),
                )

            return branch

        decode_branches = [make_decode_branch(s) for s in range(S)]
        combined_branches = [
            make_combined_branch(s) for s in range(S)
        ]

        def ring_decode(wflat, pk, pv, tables, lengths0, last0,
                        temps, active, fill_wave, fill_offs,
                        fill_tokens, fill_plens, key):
            T = int(tables.shape[1])

            def per_device(wflat, pk, pv, tables, lengths0, last0,
                           temps, active, fill_wave, fill_offs,
                           fill_tokens, fill_plens, key):
                stage = jax.lax.axis_index("stages")
                p = wflat.reshape(wflat.shape[-1])
                pk, pv = pk[0], pv[0]

                def one_tick(carry, t):
                    recv, pk, pv, outputs, key = carry
                    w_idx = (t - stage) % S
                    j = (t - stage) // S
                    jc = jnp.clip(j, 0, k - 1)
                    processing = (t >= stage) & (j < k)
                    off = w_idx * ws
                    lens_w = jax.lax.dynamic_slice(
                        lengths0, (off,), (ws,)
                    )
                    act_w = jax.lax.dynamic_slice(
                        active, (off,), (ws,)
                    ) & processing
                    temps_w = jax.lax.dynamic_slice(
                        temps, (off,), (ws,)
                    )
                    last_w = jax.lax.dynamic_slice(
                        last0, (off,), (ws,)
                    )
                    tab_w = jax.lax.dynamic_slice(
                        tables, (off, 0), (ws, T)
                    )
                    pos_w = jnp.minimum(lens_w + j, maxlen - 1)
                    # wave w's token j-1, sampled by the last stage
                    # one tick ago, arrives on the ring's wrap edge
                    # EXACTLY when stage 0 needs it (waves == stages)
                    tok_in = jnp.where(
                        j == 0, last_w, recv[:ws].astype(jnp.int32)
                    )
                    key, sub = jax.random.split(key)
                    if enable_fill:
                        fw = fill_wave[w_idx]
                        f_offs_w = jax.lax.dynamic_slice(
                            fill_offs, (off,), (ws,)
                        ) + jc * Cf
                        f_rows = jax.lax.dynamic_slice(
                            fill_tokens, (off, jc * Cf), (ws, Cf)
                        )
                        f_plens_w = jax.lax.dynamic_slice(
                            fill_plens, (off,), (ws,)
                        )
                        out_flat, pk, pv = jax.lax.switch(
                            stage,
                            [
                                (lambda *a, _br=br: _br(*a))
                                for br in combined_branches
                            ],
                            p, tok_in, recv, pk, pv, pos_w, act_w,
                            temps_w, tab_w, sub, fw, f_rows,
                            f_offs_w, f_plens_w,
                        )
                    else:
                        out_flat, pk, pv = jax.lax.switch(
                            stage,
                            [
                                (lambda *a, _br=br: _br(*a))
                                for br in decode_branches
                            ],
                            p, tok_in, recv, pk, pv, pos_w, act_w,
                            temps_w, tab_w, sub,
                        )
                    toks = out_flat[:ws].astype(jnp.int32)
                    upd = jax.lax.dynamic_update_slice(
                        outputs, toks[None, :], (jc, off)
                    )
                    outputs = jnp.where(
                        (stage == S - 1) & processing, upd, outputs
                    )
                    recv = jax.lax.ppermute(
                        out_flat, "stages",
                        [(i, (i + 1) % S) for i in range(S)],
                    )
                    return (recv, pk, pv, outputs, key), None

                recv0 = jnp.zeros((B_dec,), jnp.float32)
                out0 = jnp.zeros((k, num_slots), jnp.int32)
                (recv, pk, pv, outputs, key), _ = jax.lax.scan(
                    one_tick, (recv0, pk, pv, out0, key),
                    jnp.arange(S * k + S - 1),
                )
                return pk[None], pv[None], outputs[None], key

            return shard_map_compat(
                per_device,
                mesh=mesh,
                in_specs=(param_spec, pool_spec, pool_spec,
                          P(), P(), P(), P(), P(), P(), P(), P(),
                          P(), P()),
                out_specs=(pool_spec, pool_spec, P("stages"), P()),
                check=False,
            )(wflat, pk, pv, tables, lengths0, last0, temps, active,
              fill_wave, fill_offs, fill_tokens, fill_plens, key)

        self._decode_ring_jit = jax.jit(
            ring_decode, donate_argnums=(1, 2)
        )

        # -- prefill ring: one chunk per wave walks all stages --------

        def make_chunk_branch(s: int, C: int):
            nodes, in_kt, out_kt = plan.programs[s]
            first, last = s == 0, s == S - 1
            D_in = None if first else plan.boundary_dims[s - 1]
            unravel, p_size = unravels[s], p_sizes[s]
            B_pre = ws * C * D_max

            def branch(p, rows, recv, pk, pv, offs_w, clens_w,
                       act_w, p_lens_w, temps_w, tab_w, sub):
                pos_mat = offs_w[:, None] + jnp.arange(C)[None, :]
                valid = act_w[:, None] & (
                    jnp.arange(C)[None, :] < clens_w[:, None]
                )
                ctx = {
                    "w": unravel(p[:p_size]),
                    "pk": pk, "pv": pv, "updated": {},
                    "pos_mat": pos_mat, "valid": valid,
                    "tables": tab_w,
                }
                handler = self._make_stage_handler(s, "chunk", ctx)
                x = rows if first else (
                    recv[: ws * C * D_in].reshape(ws, C, D_in)
                )
                out = _replay_nodes(nodes, in_kt, out_kt, x, handler)
                for li, (nk, nv) in sorted(ctx["updated"].items()):
                    pk = pk.at[li].set(nk)
                    pv = pv.at[li].set(nv)
                if last:
                    at_end = (
                        (p_lens_w - offs_w - 1)[:, None]
                        == jnp.arange(C)[None, :]
                    ).astype(out.dtype)
                    row = jnp.einsum("wc,wcv->wv", at_end, out)
                    firsts = _sample_dynamic(
                        row, sub, temps_w, top_k, top_p
                    )
                    flat = firsts.astype(jnp.float32)
                else:
                    flat = out.reshape(-1)
                return (
                    jnp.pad(flat, (0, B_pre - flat.size)), pk, pv,
                )

            return branch

        def ring_prefill(wflat, pk, pv, tables, tokens, offs, clens,
                         act, p_lens, temps, key):
            C = int(tokens.shape[1])
            T = int(tables.shape[1])
            B_pre = ws * C * D_max
            branches = [make_chunk_branch(s, C) for s in range(S)]

            def per_device(wflat, pk, pv, tables, tokens, offs,
                           clens, act, p_lens, temps, key):
                stage = jax.lax.axis_index("stages")
                p = wflat.reshape(wflat.shape[-1])
                pk, pv = pk[0], pv[0]

                def one_tick(carry, t):
                    recv, pk, pv, firsts, key = carry
                    w_idx = (t - stage) % S
                    processing = (t >= stage) & (t - stage < S)
                    off = w_idx * ws
                    rows = jax.lax.dynamic_slice(
                        tokens, (off, 0), (ws, C)
                    )
                    offs_w = jax.lax.dynamic_slice(
                        offs, (off,), (ws,)
                    )
                    clens_w = jax.lax.dynamic_slice(
                        clens, (off,), (ws,)
                    )
                    act_w = jax.lax.dynamic_slice(
                        act, (off,), (ws,)
                    ) & processing
                    p_lens_w = jax.lax.dynamic_slice(
                        p_lens, (off,), (ws,)
                    )
                    temps_w = jax.lax.dynamic_slice(
                        temps, (off,), (ws,)
                    )
                    tab_w = jax.lax.dynamic_slice(
                        tables, (off, 0), (ws, T)
                    )
                    key, sub = jax.random.split(key)
                    out_flat, pk, pv = jax.lax.switch(
                        stage,
                        [
                            (lambda *a, _br=br: _br(*a))
                            for br in branches
                        ],
                        p, rows, recv, pk, pv, offs_w, clens_w,
                        act_w, p_lens_w, temps_w, tab_w, sub,
                    )
                    toks = out_flat[:ws].astype(jnp.int32)
                    upd = jax.lax.dynamic_update_slice(
                        firsts, toks, (off,)
                    )
                    firsts = jnp.where(
                        (stage == S - 1) & processing, upd, firsts
                    )
                    recv = jax.lax.ppermute(
                        out_flat, "stages",
                        [(i, (i + 1) % S) for i in range(S)],
                    )
                    return (recv, pk, pv, firsts, key), None

                recv0 = jnp.zeros((B_pre,), jnp.float32)
                f0 = jnp.zeros((num_slots,), jnp.int32)
                (recv, pk, pv, firsts, key), _ = jax.lax.scan(
                    one_tick, (recv0, pk, pv, f0, key),
                    jnp.arange(2 * S - 1),
                )
                return pk[None], pv[None], firsts[None], key

            return shard_map_compat(
                per_device,
                mesh=mesh,
                in_specs=(param_spec, pool_spec, pool_spec, P(), P(),
                          P(), P(), P(), P(), P(), P()),
                out_specs=(pool_spec, pool_spec, P("stages"), P()),
                check=False,
            )(wflat, pk, pv, tables, tokens, offs, clens, act,
              p_lens, temps, key)

        self._prefill_ring_jit = jax.jit(
            ring_prefill, donate_argnums=(1, 2)
        )

        # -- per-stage offload gather / resume scatter -----------------

        def gather_rows(pk, pv, ids):
            def per_device(pk, pv, ids):
                pk, pv = pk[0], pv[0]
                gk = jnp.take(pk, ids, axis=1, mode="clip")
                gv = jnp.take(pv, ids, axis=1, mode="clip")
                return gk[None], gv[None]

            return shard_map_compat(
                per_device, mesh=mesh,
                in_specs=(pool_spec, pool_spec, P()),
                out_specs=(pool_spec, pool_spec),
                check=False,
            )(pk, pv, ids)

        def scatter_rows(pk, pv, ids, rk, rv):
            def per_device(pk, pv, ids, rk, rv):
                pk, pv, rk, rv = pk[0], pv[0], rk[0], rv[0]
                pk = pk.at[:, ids].set(rk, mode="drop")
                pv = pv.at[:, ids].set(rv, mode="drop")
                return pk[None], pv[None]

            return shard_map_compat(
                per_device, mesh=mesh,
                in_specs=(pool_spec, pool_spec, P(), pool_spec,
                          pool_spec),
                out_specs=(pool_spec, pool_spec),
                check=False,
            )(pk, pv, ids, rk, rv)

        self._gather_jit = jax.jit(gather_rows)
        self._scatter_jit = jax.jit(
            scatter_rows, donate_argnums=(0, 1)
        )

    # -- dispatch + compile accounting ----------------------------------

    def _dispatch(self, program: str, fn, *args):
        """Cache-size-watched dispatch (the ISSUE 12 pattern): a call
        that grew the program's jit cache records a ``jit.compile``
        span. Report-only; unwatched under null mode."""
        if not self._trace_compiles:
            return fn(*args)
        try:
            before = int(fn._cache_size())
        except Exception:  # jax-version drift: dispatch unwatched
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        try:
            grew = int(fn._cache_size()) > before
        except Exception:
            grew = False
        if grew:
            self._tracer.complete(
                "jit.compile", time.perf_counter() - t0,
                program=program, engine=self.telemetry_label,
            )
        return out

    # -- request API ----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, eos_id: int | None = None,
               on_token=None, priority: int = 0) -> Request:
        """Queue one generation request (admitted at the next window
        boundary — mid-flight submission joins the next wave). Same
        shape as ``InferenceEngine.submit`` minus the policy/tenant
        knobs this engine does not carry; ``priority`` matters only
        with ``preemption=True``."""
        prompt = np.asarray(prompt).reshape(-1)
        p = len(prompt)
        if p < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} < 1")
        if p + max_new_tokens > self.maxlen:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model's maxlen ({self.maxlen})"
            )
        if temperature < 0:
            raise ValueError(f"temperature={temperature} < 0")
        self.scheduler.bucket_for(p)  # fail here, not mid-wave
        if priority and not self.preemption:
            logger.warning(
                "submit(priority=%d) on a PP engine without "
                "preemption=True — priority is recorded but IGNORED",
                priority,
            )
        req = self.scheduler.make_request(
            prompt, max_new_tokens, temperature=temperature,
            eos_id=eos_id, on_token=on_token, priority=priority,
        )
        req.submit_time = time.perf_counter()
        req.submit_step = self.scheduler._steps
        req.exemplar = {"rid": str(req.rid)}
        self._tracer.emit(
            "serve.submit", rid=req.rid, prompt_tokens=p,
            max_new_tokens=int(max_new_tokens),
            step=req.submit_step,
        )
        need = blocks_for(p + max_new_tokens, self.block_size)
        if need > self.num_blocks:
            req.error = RuntimeError(
                f"request {req.rid} needs {need} KV blocks per stage "
                f"(prompt {p} + max_new_tokens {max_new_tokens} at "
                f"block_size {self.block_size}) but each stage pool "
                f"only has {self.num_blocks} — it can never be "
                f"admitted; rejected at submit"
            )
            req.done = True
            self._m_rejected.inc()
            logger.warning("%s", req.error)
            self.finished[req.rid] = req
            self._evict_finished()
            return req
        self.scheduler.submit(req)
        return req

    def _evict_finished(self) -> None:
        while len(self.finished) > self._finished_bound:
            victim = next(
                (rid for rid in self.finished
                 if rid not in self._protected),
                None,
            )
            if victim is None:
                return
            self.finished.pop(victim)
            self._tracer.emit("serve.evict", rid=victim)

    def _emit(self, req: Request, token: int) -> bool:
        """Record one generated token; reclaim the slot when the
        request finished (EOS / budget / raising callback)."""
        self._m_tokens.inc()
        slot = req.slot
        now = time.perf_counter()
        req.token_times.append(now)
        if len(req.token_times) == 1:
            self._tracer.emit(
                "serve.first_token", rid=req.rid,
                step=self.scheduler._steps,
            )
            if req.submit_time is not None:
                self._m_ttft.observe(
                    now - req.submit_time, exemplar=req.exemplar
                )
        else:
            self._m_itl.observe(
                now - req.token_times[-2], exemplar=req.exemplar
            )
        done = self.scheduler.on_token(slot, token)
        if req.on_token is not None:
            try:
                req.on_token(token, done)
            except Exception as e:
                req.error = e
                req.done = True
                done = True
                logger.warning(
                    "request %d failed in its on_token callback (%r) "
                    "— slot %d reclaimed, engine continues",
                    req.rid, e, slot,
                )
        if done:
            req.finish_time = req.token_times[-1]
            self.scheduler.reclaim(slot)
            self._active_host[slot] = False
            self._m_finished.inc()
            if req.error is not None:
                reason = "callback_error"
            elif (
                req.eos_id is not None and req.tokens
                and req.tokens[-1] == req.eos_id
            ):
                reason = "eos"
            else:
                reason = "budget"
            self._tracer.emit(
                "serve.finish", rid=req.rid, reason=reason,
                tokens=len(req.tokens), step=self.scheduler._steps,
            )
            self.finished[req.rid] = req
            self._evict_finished()
        return done

    # -- device staging of host truth -----------------------------------

    def _staged_tables(self):
        """Device copy of the block tables, ``[num_slots, T]`` for the
        bucketed ``T`` — sentinel-padded, rebuilt only on mutation or
        bucket shift (the paged engine's caching pattern)."""
        sched = self.scheduler
        need = max(
            (len(t) for t in sched.tables.values()), default=1
        )
        T = table_bucket_for(need, self._tbuckets)
        key = (sched.tables_version, T)
        if self._tables_cache is None or self._tables_cache[0] != key:
            arr = np.full(
                (self.num_slots, T), self.num_blocks, np.int32
            )
            for slot, table in sched.tables.items():
                arr[slot, : len(table)] = table
            self._tables_cache = (key, self._stage_host(arr))
        return self._tables_cache[1]

    def _pad_ids(self, blocks):
        Tb = table_bucket_for(max(1, len(blocks)), self._tbuckets)
        ids = np.full((Tb,), self.num_blocks, np.int32)
        ids[: len(blocks)] = blocks
        return ids

    # -- preemption offload / resume ------------------------------------

    def _offload(self, pre) -> None:
        """Per-stage offload: gather the victim's blocks from EVERY
        stage's pool in one stage-sharded program, host-read the
        stacked rows, and park them until resume. Runs before any
        pool-writing program of the same step (the jit data dependency
        orders the gather against the current pool value)."""
        req = pre.req
        with self._tracer.span(
            "serve.preempt", rid=req.rid, blocks=len(pre.blocks),
        ):
            ids = self._pad_ids(pre.blocks)
            gk, gv = self._dispatch(
                "pp_offload_gather", self._gather_jit,
                self._pk, self._pv, self._stage_host(ids),
            )
            n = len(pre.blocks)
            k_rows = np.ascontiguousarray(self._host(gk)[:, :, :n])
            v_rows = np.ascontiguousarray(self._host(gv)[:, :, :n])
            self._offloaded[req.rid] = _StageOffload(
                k_rows=k_rows, v_rows=v_rows, n_blocks=n,
                cur_len=pre.cur_len,
            )
        self._active_host[pre.slot] = False
        self._m_preemptions.inc()
        self._m_offload_blocks.inc(n * self.num_stages)
        logger.info(
            "PP-preempted request %d: %d blocks/stage offloaded "
            "across %d stages, slot %d freed",
            req.rid, n, self.num_stages, pre.slot,
        )

    def _resume(self, adm) -> None:
        """Scatter the parked per-stage rows into the fresh allocation
        and re-arm host state — bit-exact: greedy decode is a pure
        function of (weights, K/V, cursor, last token), and the
        restored rows are bitwise the offloaded ones on every
        stage."""
        from elephas_tpu.parallel.mesh import put_global

        req = adm.req
        store = self._offloaded.pop(req.rid)
        with self._tracer.span(
            "serve.resume", rid=req.rid, blocks=store.n_blocks,
        ):
            n = store.n_blocks
            ids = self._pad_ids(adm.blocks[:n])
            Tb = len(ids)
            S = self.num_stages
            shape = (
                S, self.layers_per_stage, Tb, self.block_size,
                self.num_heads, self.head_dim,
            )
            rk = np.zeros(shape, np.float32)
            rv = np.zeros(shape, np.float32)
            rk[:, :, :n] = store.k_rows
            rv[:, :, :n] = store.v_rows
            self._pk, self._pv = self._dispatch(
                "pp_resume_scatter", self._scatter_jit,
                self._pk, self._pv, self._stage_host(ids),
                put_global(rk, self._pool_sh),
                put_global(rv, self._pool_sh),
            )
        self._active_host[adm.slot] = True
        self._m_resumes.inc()  # admission kind counted by admit_paged
        logger.info(
            "PP-resumed request %d into slot %d (%d blocks/stage, "
            "cursor %d)", req.rid, adm.slot, n, store.cur_len,
        )

    # -- execution ------------------------------------------------------

    def _account_ring(self, stage_ticks: int, useful: int) -> None:
        """Cumulative ring-time accounting (report-only): every ring
        dispatch — decode window or standalone prefill — schedules
        ``stage_ticks`` stage-ticks of which ``useful`` carried wave
        work; ``stats()['bubble_cumulative']`` is the lifetime idle
        fraction, the number the bubble-fill bench compares across
        arms (a filled arm simply never schedules the standalone
        prefill dispatch's mostly-idle ticks)."""
        self._ticks_sched += int(stage_ticks)
        self._ticks_useful += int(useful)

    def _prefill_wave(self, fresh):
        """Prefill an admission wave through the ring: one dispatch
        per prompt-width bucket walks every admitted slot's prompt
        through all stages (wave by wave), lands each stage's K/V in
        its own pool, and samples first tokens on the last stage.

        Suffix-bucketed (ISSUE 16): a prefix-index hit spliced blocks
        that are ALREADY resident on every stage (one allocator, one
        id fleet-wide), so the ring starts at the shared offset and
        buckets by the remaining suffix — the flat engine's rule."""
        items = []
        for a in fresh:
            if a.shared_len:
                self._m_prefix_shared.inc(a.shared_len)
            items.append((a.req, a.slot, a.shared_len))
        return self._ring_prefill_items(items, demoted=False)

    def _ring_prefill_items(self, items, demoted: bool):
        """Run the offset-capable prefill ring over ``(req, slot,
        offset)`` items — admission waves (offset = shared prefix) and
        bubble-fill demotions (offset = fill progress) share one
        dispatch path, so both complete the prompt, register it with
        the prefix index, and emit the first token identically."""
        emitted = []
        sched = self.scheduler
        S, ws = self.num_stages, self.wave_slots
        by_width: dict[int, list] = {}
        for req, slot, off in items:
            by_width.setdefault(
                sched.bucket_for(len(req.prompt) - off), []
            ).append((req, slot, off))
        for width in sorted(by_width):
            group = by_width[width]
            tokens = np.zeros((self.num_slots, width), np.int32)
            offs = np.zeros((self.num_slots,), np.int32)
            clens = np.zeros((self.num_slots,), np.int32)
            act = np.zeros((self.num_slots,), bool)
            p_lens = np.ones((self.num_slots,), np.int32)
            temps = np.zeros((self.num_slots,), np.float32)
            for req, slot, off in group:
                suffix = req.prompt[off:]
                tokens[slot, : len(suffix)] = suffix
                offs[slot] = off
                clens[slot] = len(suffix)
                act[slot] = True
                p_lens[slot] = len(req.prompt)
                temps[slot] = req.temperature
            # a standalone prefill dispatch is ring time too: 2S−1
            # ticks in which only the occupied waves carry work
            waves_used = len({slot // ws for _r, slot, _o in group})
            self._account_ring(S * (2 * S - 1), waves_used * S)
            with self._tracer.span(
                "serve.prefill_wave", reqs=len(group), width=width,
                demoted=bool(demoted),
            ):
                (self._pk, self._pv, firsts, self._key) = (
                    self._dispatch(
                        "pp_ring_prefill", self._prefill_ring_jit,
                        self._wflat, self._pk, self._pv,
                        self._staged_tables(),
                        self._stage_host(tokens),
                        self._stage_host(offs),
                        self._stage_host(clens),
                        self._stage_host(act),
                        self._stage_host(p_lens),
                        self._stage_host(temps), self._key,
                    )
                )
                toks = self._host(firsts)[self.num_stages - 1]
            for req, slot, off in group:
                self._active_host[slot] = True
                self._tracer.emit(
                    "serve.prefill", rid=req.rid, bucket=width,
                    prompt_tokens=len(req.prompt), offset=int(off),
                    step=sched._steps,
                )
                # register the completed prompt with the prefix index
                # BEFORE emitting: a budget-1 request reclaims its
                # table inside _emit, and insert() needs it live
                sched.on_prefill_complete(req)
                self._emit(req, int(toks[slot]))
                emitted.append((req, req.tokens[-1], req.done))
        return emitted

    def _decode_window(self):
        """One compiled window of ``S·k + S − 1`` ring ticks: every
        wave advances ``k`` tokens, stages overlap on different waves
        (the bubble-filling schedule), host state re-arms from truth
        at the boundary.

        Bubble-fill (ISSUE 16): waves flagged in ``fill_wave`` run
        the CHUNK branch on their ticks instead of decode — each of
        the window's ``k`` rounds advances every filler in the wave
        by ``bubble_chunk`` prompt positions (from its ``_filling``
        offset). A filler whose remaining prompt fits this window
        samples its first token at round ``ceil(remaining/C) − 1``
        and parks in ``_fill_done`` until the WHOLE wave finished
        filling (whole-wave graduation — graduating one slot early
        would flip the wave to decode and strand its co-fillers
        mid-prompt); then the wave decodes normally from the next
        window."""
        sched = self.scheduler
        S, ws, k = self.num_stages, self.wave_slots, self.steps_per_wave
        C = self._C
        filling = dict(self._filling)
        skip = set(filling) | self._fill_done
        lengths0 = np.zeros((self.num_slots,), np.int32)
        last0 = np.zeros((self.num_slots,), np.int32)
        temps = np.zeros((self.num_slots,), np.float32)
        for slot, req in sched.active.items():
            temps[slot] = req.temperature
            if slot in skip:
                continue  # fillers have no sampled token yet
            lengths0[slot] = len(req.prompt) + len(req.tokens) - 1
            last0[slot] = req.tokens[-1]
        active = self._active_host.copy()
        fill_wave = np.zeros((S,), bool)
        fill_offs = np.zeros((self.num_slots,), np.int32)
        fill_tokens = np.zeros((self.num_slots, k * C), np.int32)
        fill_plens = np.ones((self.num_slots,), np.int32)
        fill_rounds = [0] * S
        fill_toks = 0
        for slot, off in sorted(filling.items()):
            req = sched.active[slot]
            w = slot // ws
            pl = len(req.prompt)
            fill_wave[w] = True
            fill_offs[slot] = off
            seg = req.prompt[off: off + k * C]
            fill_tokens[slot, : len(seg)] = seg
            fill_plens[slot] = pl
            active[slot] = True
            rounds = min(-(-(pl - off) // C), k)
            fill_rounds[w] = max(fill_rounds[w], rounds)
            fill_toks += len(seg)
        for slot in self._fill_done:
            # completed co-fillers keep their wave in fill mode (the
            # chunk branch idles them via the valid mask) until the
            # whole wave graduates
            fill_wave[slot // ws] = True
        # report-only wave occupancy + bubble fraction: ramp/drain
        # ticks plus whole-window ticks of EMPTY waves carry no wave
        # work — but rounds a fill wave spends on prefill chunks DO;
        # telemetry observes, never drives
        wave_live = [
            int(self._active_host[w * ws:(w + 1) * ws].sum())
            for w in range(S)
        ]
        nonempty = sum(1 for n in wave_live if n)
        ticks = S * k + S - 1
        useful = nonempty * S * k + sum(fill_rounds) * S
        bubble = 1.0 - useful / float(S * ticks)
        self._last_bubble = bubble
        self._m_bubble.set(bubble)
        self._account_ring(S * ticks, useful)
        for w, n in enumerate(wave_live):
            self._mf_wave_active.labels(
                engine=self.telemetry_label, wave=str(w)
            ).set(n)
        if fill_toks:
            self._m_fill_tokens.inc(fill_toks)
            self._m_fill_rounds.inc(sum(fill_rounds))
        emitted = []
        with self._tracer.span(
            "serve.wave", waves=S, steps=k,
            active=len(sched.active), bubble=round(bubble, 4),
            fill_slots=len(filling), fill_tokens=fill_toks,
        ):
            self._m_decode_windows.inc()
            (self._pk, self._pv, outputs, self._key) = self._dispatch(
                "pp_ring_decode", self._decode_ring_jit,
                self._wflat, self._pk, self._pv,
                self._staged_tables(), self._stage_host(lengths0),
                self._stage_host(last0), self._stage_host(temps),
                self._stage_host(active),
                self._stage_host(fill_wave),
                self._stage_host(fill_offs),
                self._stage_host(fill_tokens),
                self._stage_host(fill_plens),
                self._key,
            )
            toks = self._host(outputs)[S - 1]  # [k, num_slots]
            for i in range(k):
                if not sched.active:
                    break
                sched.note_step()
                for slot, req in sorted(sched.active.items()):
                    if slot in skip:
                        continue
                    done = self._emit(req, int(toks[i, slot]))
                    emitted.append((req, req.tokens[-1], done))
        # fill advancement/completion at the window boundary
        for slot in sorted(filling):
            req = sched.active.get(slot)
            if req is None or slot not in self._filling:
                continue  # cancelled mid-window
            off = self._filling[slot]
            pl = len(req.prompt)
            rounds = -(-(pl - off) // C)  # ceil
            if rounds <= k:
                del self._filling[slot]
                self._tracer.emit(
                    "serve.fill_complete", rid=req.rid, slot=slot,
                    prompt_tokens=pl, step=sched._steps,
                )
                sched.on_prefill_complete(req)
                done = self._emit(req, int(toks[rounds - 1, slot]))
                emitted.append((req, req.tokens[-1], done))
                if not done:
                    self._fill_done.add(slot)
            else:
                self._filling[slot] = off + k * C
        # whole-wave graduation: a fill wave with no in-progress
        # fillers left starts decoding at the NEXT window
        for slot in sorted(self._fill_done):
            w = slot // ws
            if not any(s // ws == w for s in self._filling):
                self._fill_done.discard(slot)
                self._active_host[slot] = True
        return emitted

    def _start_fill(self, a) -> None:
        """Arm bubble-fill for one admission: the prompt (its suffix,
        after a prefix-hit splice — shared blocks are resident on
        every stage already) prefills through the slot's wave during
        coming decode windows' idle ticks instead of a standalone
        ring dispatch. The slot stays decode-inactive until its wave
        graduates."""
        req = a.req
        if a.shared_len:
            self._m_prefix_shared.inc(a.shared_len)
        self._filling[a.slot] = int(a.shared_len)
        self._tracer.emit(
            "serve.fill_admit", rid=req.rid, slot=a.slot,
            prompt_tokens=len(req.prompt), shared=int(a.shared_len),
            step=self.scheduler._steps,
        )

    def _demote_stranded(self):
        """Bubble-fill liveness: fillers whose wave can no longer run
        as a PURE fill wave finish their prompts NOW through the
        offset prefill ring. Two strandings exist: (a) a decode-
        active occupant landed in the fill wave (a resume's wave-
        aware slot, or a budget-overflow admission) — each ring tick
        runs ONE branch per wave, so mixed waves are unschedulable;
        (b) no decode-active wave remains anywhere, so no window
        would ever carry the fill. Demotion re-enters the standing
        prefill path at the CURRENT offset — chunk K/V already
        written stays valid, only the remaining suffix rings."""
        if not self._filling and not self._fill_done:
            return []
        ws = self.wave_slots
        decode_waves = {
            int(s) // ws for s in np.flatnonzero(self._active_host)
        }
        demote = [
            slot for slot in sorted(self._filling)
            if slot // ws in decode_waves or not decode_waves
        ]
        emitted = []
        if demote:
            items = []
            for slot in demote:
                off = self._filling.pop(slot)
                req = self.scheduler.active[slot]
                self._tracer.emit(
                    "serve.fill_demote", rid=req.rid, slot=slot,
                    offset=int(off), step=self.scheduler._steps,
                )
                items.append((req, slot, off))
            emitted = self._ring_prefill_items(items, demoted=True)
        # completed fillers in a wave that is decoding anyway (or
        # whose last in-progress filler just demoted) graduate now
        for slot in sorted(self._fill_done):
            w = slot // ws
            if w in decode_waves or not any(
                s // ws == w for s in self._filling
            ):
                self._fill_done.discard(slot)
                self._active_host[slot] = True
        return emitted

    def step(self):
        """One engine iteration: paged admission (preemption offloads
        first, resumes restored, bubble-fill admissions armed, fresh
        admissions ring-prefilled, stranded fillers demoted), then
        one microbatched decode window. Returns ``(request, token,
        done)`` triples in generation order."""
        emitted = []
        fillers = frozenset(self._filling) | frozenset(self._fill_done)
        plan, preempts = self.scheduler.admit_paged(
            prefilling=fillers,
            bubble_fill=self.bubble_fill,
            fill_budget=self.bubble_budget,
        )
        for pre in preempts:
            self._offload(pre)
            # a parked-but-complete filler may be a victim; its fill
            # state dies with the slot (the offload record has the
            # full prompt K/V — resume restores it as plain decode)
            self._fill_done.discard(pre.slot)
        if plan:
            for a in plan:
                if a.resume is not None:
                    self._resume(a)
            for a in plan:
                if a.resume is None and a.fill:
                    self._start_fill(a)
            fresh = [
                a for a in plan if a.resume is None and not a.fill
            ]
            if fresh:
                emitted.extend(self._prefill_wave(fresh))
        emitted.extend(self._demote_stranded())
        if self.scheduler.active:
            emitted.extend(self._decode_window())
        return emitted

    def _notify_stream_end(self, req: Request) -> None:
        """Tell a request's live stream it ENDED without a final
        engine token — ``on_token(None, True)``; without it a
        consumer blocking on the stream (the gateway's SSE handlers)
        waits forever on cancel."""
        cb = req.on_token
        if cb is not None:
            try:
                cb(None, True)
            except BaseException:
                logger.warning(
                    "request %d stream-end callback failed",
                    req.rid, exc_info=True,
                )

    def cancel(self, rid: int) -> bool:
        """Abort one in-flight request and reclaim its wave slot and
        blocks at the window boundary — flat-engine parity (the
        gateway's ``POST /v1/requests/{rid}/cancel`` route and SSE
        disconnects call this on whichever engine serves). A waiting
        request leaves the queue (a preempted one also drops its
        per-stage offload record); an active one frees its slot and
        table, clearing any bubble-fill state. Returns True when the
        rid was live (``req.done`` flips with ``req.error`` set to
        :class:`RequestCancelled`), False when unknown or already
        finished.

        Gang contract: every gang process must issue the identical
        cancel sequence at the identical step boundaries."""
        from elephas_tpu.serving.engine import RequestCancelled

        rid = int(rid)
        sched = self.scheduler
        req = sched.remove_waiting(rid)
        if req is not None:
            self._offloaded.pop(rid, None)
        else:
            slot = next(
                (s for s, r in sched.active.items() if r.rid == rid),
                None,
            )
            if slot is None:
                return False
            req = sched.active[slot]
            self._filling.pop(slot, None)
            self._fill_done.discard(slot)
            sched.reclaim(slot)
            self._active_host[slot] = False
        req.done = True
        req.error = RequestCancelled(f"request {rid} cancelled")
        # a live stream must UNBLOCK, not hang: cancel never delivers
        # a final token, so send the explicit end sentinel
        self._notify_stream_end(req)
        self._m_cancelled.inc()
        self._tracer.emit(
            "serve.cancel", rid=rid, tokens=len(req.tokens),
            step=sched._steps,
        )
        self.finished[rid] = req
        self._evict_finished()
        return True

    def stream(self):
        while self.scheduler.has_work:
            for req, token, done in self.step():
                yield req.rid, token, done

    def run(self, requests=None) -> dict[int, np.ndarray]:
        """Batch driver, shaped like ``InferenceEngine.run``."""
        submitted: list[Request] = []
        if requests is not None:
            for r in requests:
                if isinstance(r, dict):
                    submitted.append(self.submit(**r))
                else:
                    prompt, max_new = r
                    submitted.append(self.submit(prompt, max_new))
        protected = {r.rid for r in submitted} - self._protected
        self._protected |= protected
        try:
            drained: dict[int, np.ndarray] = {}
            while self.scheduler.has_work:
                for req, _tok, done in self.step():
                    if done:
                        drained[req.rid] = np.asarray(
                            req.full_sequence, np.int32
                        )
        finally:
            self._protected -= protected
            self._evict_finished()
        return drained

    # -- introspection --------------------------------------------------

    @property
    def total_generated(self) -> int:
        return int(self._m_tokens.value)

    @property
    def finished_count(self) -> int:
        return int(self._m_finished.value)

    def compile_stats(self) -> dict:
        """Compiled-program counts — the closed-set contract: the
        decode ring compiles once per table bucket, the prefill ring
        once per (width bucket, table bucket), gather/scatter once
        per touched table bucket. A second identical workload must
        leave this dict unchanged."""

        def n(f):
            try:
                return int(f._cache_size())
            except Exception:  # pragma: no cover - jax-version drift
                return -1

        return {
            "ring_decode_compiles": n(self._decode_ring_jit),
            "ring_prefill_compiles": n(self._prefill_ring_jit),
            "offload_compiles": n(self._gather_jit),
            "resume_compiles": n(self._scatter_jit),
            "buckets": tuple(self.scheduler.buckets),
            "table_buckets": tuple(self._tbuckets),
            "num_stages": self.num_stages,
            "wave_slots": self.wave_slots,
            "steps_per_wave": self.steps_per_wave,
            "block_size": self.block_size,
            "num_blocks": self.num_blocks,
            "model_parallel": self.model_parallel,
            "attention": self.attention,
            "bubble_fill": self.bubble_fill,
            "bubble_chunk": self._C,
            "prefix_cache": self.scheduler.prefix_index is not None,
        }

    def stats(self) -> dict:
        finished = list(self.finished.values())
        lat = [
            r.finish_time - r.submit_time
            for r in finished
            if r.finish_time is not None and r.submit_time is not None
        ]
        ttfts = [r.ttft for r in finished if r.ttft is not None]
        itls = [d for r in finished for d in r.inter_token_times]
        d_toks = sum(
            len(r.token_times) - 1
            for r in finished if len(r.token_times) > 1
        )
        d_secs = sum(
            r.token_times[-1] - r.token_times[0]
            for r in finished if len(r.token_times) > 1
        )
        from elephas_tpu.serving.engine import InferenceEngine

        pct = InferenceEngine._percentiles
        return {
            "total_generated": self.total_generated,
            "finished": self.finished_count,
            "decode_steps": self.scheduler._steps,
            "occupancy": self.scheduler.occupancy,
            "latencies": lat,
            "num_slots": self.num_slots,
            "num_stages": self.num_stages,
            "wave_slots": self.wave_slots,
            "steps_per_wave": self.steps_per_wave,
            "attention": self.attention,
            "ttft_s": pct(ttfts),
            "inter_token_s": pct(itls),
            "decode_tok_s": (d_toks / d_secs) if d_secs > 0 else None,
            "queue_depth": int(self.scheduler._m_waiting.value),
            "preemptions": int(self._m_preemptions.value),
            "resumes": int(self._m_resumes.value),
            "rejected": int(self._m_rejected.value),
            "offloaded_blocks": int(self._m_offload_blocks.value),
            "blocks_total": self.num_blocks,
            "blocks_free": self.scheduler.allocator.free_count,
            "bubble_fraction": self._last_bubble,
            "bubble_cumulative": (
                1.0 - self._ticks_useful / self._ticks_sched
                if self._ticks_sched else None
            ),
            "fill_tokens": int(self._m_fill_tokens.value),
            "fill_rounds": int(self._m_fill_rounds.value),
            "prefix_shared_tokens": int(self._m_prefix_shared.value),
            "cancelled": int(self._m_cancelled.value),
        }

    def scrape(self, full: bool = True) -> str:
        """Prometheus exposition of this engine's series (the
        ``InferenceEngine.scrape`` shape, 0.0.4 flavor)."""
        if not full:
            reg = self._telemetry_registry
            return telemetry.render(
                reg, only={"engine": self.telemetry_label}
            ) + telemetry.render(
                reg, only={"scheduler": self.scheduler.telemetry_label}
            )
        return telemetry.render(self._telemetry_registry)

    def release_telemetry(self) -> None:
        telemetry.remove_series(engine=self.telemetry_label)
        self.scheduler.release_telemetry()
