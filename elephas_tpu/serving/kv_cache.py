"""Slot-based KV cache arena + the token/prefill graph replays.

The arena is a fixed ``[num_slots, max_len, heads, head_dim]`` pair of
K/V buffers per attention layer — the serving analogue of
``generate(kv_cache=True)``'s per-call caches, except slots outlive any
single request: a slot is a lease, its **write cursor** (the per-slot
position vector threaded through the decode step) marks how many
tokens of the current occupant are cached, and reclaiming a slot is
free (the next occupant's prefill simply overwrites from position 0;
stale rows beyond the new prompt are never visible because causal
decode only attends positions ``<= cursor`` and every such position is
rewritten before the cursor reaches it).

Sharded exactly like the mesh-aware decode path: the slot axis rides
the batch axes, heads ride the model axis when they tile
(:func:`SlotKVCache.constrain` mirrors ``_generate_cached``'s
``_constrain_cache`` rules), so the arena of a TP-sharded model lives
sharded for the server's whole lifetime.

Two graph replays produce/consume the arena, both built on keras'
``Function._run_through_graph`` node traversal (the mechanism proven
by ``generate(kv_cache=True)``):

- :func:`token_decode_step` — ONE token per slot, at per-slot
  positions (a *vector* cursor — this is what lets sequences of
  different lengths decode in the same compiled program; the one-shot
  path only ever needed a scalar ``t``);
- :func:`prefill_forward` — a whole (bucket-padded) prompt for one
  slot as a single full-sequence forward, writing every position's K/V
  into the slot row at once instead of token-by-token.
"""

from __future__ import annotations

import numpy as np

from elephas_tpu.models.transformer import (
    _apply_rope,
    _flash_mha_layer,
    _rope_tables,
)


def _is_concrete(a):
    import jax

    return isinstance(a, np.ndarray) or (
        isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer)
    )


def _squeeze_table(arr, maxlen):
    """Collapse a recorded ``[1, ..., maxlen, D]`` broadcast table to
    ``[maxlen, D]`` (the positional-table shape the graph records)."""
    import jax.numpy as jnp

    lead = arr.shape[:-2]
    if any(int(d) != 1 for d in lead):
        raise ValueError(
            f"serving decode cannot replay a concrete graph constant of "
            f"shape {arr.shape}: non-broadcast leading dims over the "
            f"sequence axis"
        )
    return jnp.reshape(arr, (maxlen, arr.shape[-1]))


def _rows_at_positions(table, positions):
    """``table[positions]`` as a one-hot matmul: per-row dynamic
    gathers on arrays whose batch axis is sharded over the mesh make
    GSPMD emit collectives INSIDE the decode loop (measured ~15× step
    cost on the CPU mesh); the one-hot contraction is slot-local and
    bit-exact (each row sums exactly one 1.0·value against 0.0s)."""
    import jax.numpy as jnp

    onehot = (
        positions[:, None] == jnp.arange(table.shape[0])[None, :]
    )
    if jnp.issubdtype(table.dtype, jnp.floating):
        return onehot.astype(table.dtype) @ table
    # integer/bool tables (e.g. a recorded position-ids arange): exact
    # select-and-sum — the mask broadcasts [B, L, 1] against [1, L, D]
    gathered = jnp.where(onehot[:, :, None], table[None], 0).sum(axis=1)
    return gathered.astype(table.dtype)


def _slice_seq_at_positions(a, positions, maxlen):
    """Decode-time analogue of ``_generate_cached``'s ``_slice_seq``
    with a VECTOR cursor: concrete arrays spanning the sequence axis
    follow each slot's own position (``[.., maxlen, D]`` → ``[B, D]``
    rows, ``[maxlen]`` → ``[B]``). Traced tensors pass through."""
    import jax.numpy as jnp

    if not _is_concrete(a):
        return a
    arr = jnp.asarray(a)
    if arr.ndim >= 2 and arr.shape[-2] == maxlen:
        return _rows_at_positions(_squeeze_table(arr, maxlen), positions)
    if arr.ndim == 1 and arr.shape[0] == maxlen:
        return _rows_at_positions(arr[:, None], positions)[:, 0]
    return a


def _slice_seq_prefix(a, s, maxlen):
    """Prefill-time slice: concrete arrays spanning the sequence axis
    truncate to the first ``s`` (bucket) positions."""
    import jax.numpy as jnp

    if not _is_concrete(a):
        return a
    arr = jnp.asarray(a)
    if arr.ndim >= 2 and arr.shape[-2] == maxlen:
        return arr[..., :s, :]
    if arr.ndim == 1 and arr.shape[0] == maxlen:
        return arr[:s]
    return a


def _rows_at_position_matrix(table, pos_mat):
    """``table[pos_mat]`` for a ``[B, C]`` position matrix as a one-hot
    contraction (``[B, C, D]`` out) — the chunked-prefill analogue of
    :func:`_rows_at_positions`: each slot's chunk sits at its own
    absolute offset, and per-row dynamic gathers on mesh-sharded
    operands would lower to collectives. Out-of-range positions (the
    padded tail of a final partial chunk) produce exact zero rows,
    which feed only masked-off garbage lanes."""
    import jax.numpy as jnp

    onehot = (
        pos_mat[:, :, None] == jnp.arange(table.shape[0])[None, None, :]
    )
    if jnp.issubdtype(table.dtype, jnp.floating):
        return jnp.einsum(
            "bcm,md->bcd", onehot.astype(table.dtype), table
        )
    gathered = jnp.where(
        onehot[..., None], table[None, None], 0
    ).sum(axis=2)
    return gathered.astype(table.dtype)


def _slice_seq_at_position_matrix(a, pos_mat, maxlen):
    """Chunk-time analogue of ``_slice_seq_prefix``: concrete arrays
    spanning the sequence axis follow each slot's absolute chunk
    positions (``[.., maxlen, D]`` → ``[B, C, D]``, ``[maxlen]`` →
    ``[B, C]``). Traced tensors pass through."""
    import jax.numpy as jnp

    if not _is_concrete(a):
        return a
    arr = jnp.asarray(a)
    if arr.ndim >= 2 and arr.shape[-2] == maxlen:
        return _rows_at_position_matrix(
            _squeeze_table(arr, maxlen), pos_mat
        )
    if arr.ndim == 1 and arr.shape[0] == maxlen:
        return _rows_at_position_matrix(arr[:, None], pos_mat)[..., 0]
    return a


def _graph_replay(model, w, x, attn_fn, slice_fn):
    """Shared graph-replay scaffold for every serving program, fixed
    arena and paged alike (ISSUE 7 refactor): FlashMHA ops route to
    ``attn_fn(op)`` — the program's attention closure, the ONLY part
    that differs between decode / prefill / chunk / paged variants —
    Dropout is identity, and every other op runs stateless with ``w``'s
    weights after ``slice_fn`` re-slices any concrete graph constant
    spanning the sequence axis (positional tables etc.)."""
    import keras

    FlashMHA = _flash_mha_layer()

    def handler(op):
        if isinstance(op, FlashMHA):
            return attn_fn(op)
        if isinstance(op, keras.layers.Dropout):
            return lambda x, *a, **k: x
        if isinstance(op, keras.Layer) and op.variables:
            def stateless(*args, _op=op, **kwargs):
                if kwargs.get("training"):
                    kwargs["training"] = False
                args = [slice_fn(a) for a in args]
                tv = [w[v.path] for v in _op.trainable_variables]
                ntv = [w[v.path] for v in _op.non_trainable_variables]
                out, _ = _op.stateless_call(tv, ntv, *args, **kwargs)
                return out

            return stateless

        def weightless(*args, _op=op, **kwargs):
            args = [slice_fn(a) for a in args]
            kwargs = {kk: slice_fn(vv) for kk, vv in kwargs.items()}
            return _op(*args, **kwargs)

        return weightless

    return model._run_through_graph(x, operation_fn=handler)


class SlotKVCache:
    """Specs + sharding rules for the slot arena of one model.

    Holds only host-side metadata (layer names/head geometry and the
    mesh layout); the arrays themselves are functional state threaded
    through the engine's jitted steps — :meth:`init` builds the zeroed
    arena, :meth:`constrain` pins a buffer's sharding inside a traced
    program."""

    def __init__(self, flash_layers, num_slots: int, max_len: int,
                 mesh=None, batch_axes=("data",), model_axis=None):
        self.specs = [
            (l.name, int(l.num_heads), int(l.head_dim))
            for l in flash_layers
        ]
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.mesh = mesh
        self.batch_axes = tuple(
            (batch_axes,) if isinstance(batch_axes, str) else batch_axes
        )
        self.model_axis = model_axis

    def nbytes(self) -> int:
        """Host-side size estimate of the full (f32) arena."""
        per_pos = sum(h * d for _, h, d in self.specs) * 2 * 4
        return self.num_slots * self.max_len * per_pos

    def constrain(self, z, heads: int):
        """``[slots, S, H, Dh]`` buffers: slots over the batch axes,
        heads over the model axis when they tile (same rule as the
        one-shot mesh decode's ``_constrain_cache``)."""
        if self.mesh is None:
            return z
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        ax = (
            self.model_axis
            if self.model_axis is not None
            and self.mesh.shape.get(self.model_axis, 1) > 1
            and heads % self.mesh.shape[self.model_axis] == 0
            else None
        )
        return jax.lax.with_sharding_constraint(
            z, NamedSharding(self.mesh, P(self.batch_axes, None, ax, None))
        )

    def init(self) -> dict:
        """The zeroed arena: ``{layer_name: (k, v)}``, each
        ``[num_slots, max_len, H, Dh]`` float32, sharded per
        :meth:`constrain` (built under jit by the engine so the zeros
        materialize directly in their sharded layout)."""
        import jax.numpy as jnp

        return {
            name: (
                self.constrain(
                    jnp.zeros(
                        (self.num_slots, self.max_len, h, d), jnp.float32
                    ),
                    h,
                ),
                self.constrain(
                    jnp.zeros(
                        (self.num_slots, self.max_len, h, d), jnp.float32
                    ),
                    h,
                ),
            )
            for name, h, d in self.specs
        }


def token_decode_step(model, w, tok, positions, caches, maxlen,
                      active=None, attention="naive", span=None):
    """One decode step for the WHOLE arena: slot ``i`` consumes token
    ``tok[i]`` at position ``positions[i]`` (its write cursor), writes
    that position's K/V into its arena row, attends over positions
    ``<= positions[i]``, and yields its next-token logits.

    Same per-row math as ``_generate_cached``'s scalar-``t`` handler
    (einsum strings and operation order kept identical so slot-decoded
    tokens match one-shot ``generate()`` exactly at temperature 0) —
    the only generalization is the vector cursor.

    ``active`` (``[num_slots]`` bool, optional) masks the cache WRITE:
    slots that are idle, mid-chunked-prefill, or resident prefix-cache
    donors must not have garbage K/V scribbled at their cursor while
    the rest of the arena decodes (ISSUE 4). Active slots' math is
    untouched — bit-identical with or without the mask.

    ``attention``/``span`` (ISSUE 11): ``attention="flash"`` routes the
    score/softmax through the tiled online-softmax kernel
    (:mod:`elephas_tpu.ops.flash_serving` — float-tolerance parity,
    temp-0 token-exact); ``span`` (a STATIC span bucket, ``None`` =
    ``maxlen``) slices the attended K/V to ``cache[:, :span]`` — the
    fixed arena's block-span read. Every ``positions[b]`` of an active
    slot must sit inside the span (the engine buckets
    ``max_resident + steps_per_sync``); an inactive lane's stale cursor
    past the span just computes masked garbage nobody reads.

    Returns ``(logits [num_slots, vocab], new_caches)``."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.ops.flash_serving import flash_span_decode

    S_att = int(maxlen if span is None else span)

    ctx_new = {}
    # write cursor as a one-hot over the sequence axis: the cache write
    # becomes an elementwise select (slot-local under the mesh — a
    # per-row scatter here would put GSPMD collectives inside the loop)
    write_mask = (
        positions[:, None] == jnp.arange(maxlen)[None, :]
    )[:, :, None, None]
    if active is not None:
        write_mask = write_mask & active[:, None, None, None]

    def attn_for(op):
        def attn(x, *_a, **_k):
            ck, cv = caches[op.name]
            H, Dh = op.num_heads, op.head_dim
            qkv = x @ w[op.qkv.kernel.path]  # [B, 3·H·Dh]
            q, k, v = jnp.split(
                qkv.reshape(x.shape[0], 3, H, Dh), 3, axis=1
            )
            q, k, v = q[:, 0], k[:, 0], v[:, 0]  # [B, H, Dh]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos_t = _rows_at_positions(
                    jnp.asarray(cos_np), positions
                )[:, None, :]
                sin_t = _rows_at_positions(
                    jnp.asarray(sin_np), positions
                )[:, None, :]
                q = _apply_rope(q, cos_t, sin_t)
                k = _apply_rope(k, cos_t, sin_t)
            ck = jnp.where(write_mask, k[:, None], ck)
            cv = jnp.where(write_mask, v[:, None], cv)
            if attention == "flash":
                o = flash_span_decode(
                    q, ck[:, :S_att], cv[:, :S_att], positions,
                    scale=Dh**-0.5,
                ).reshape(x.shape[0], H * Dh)
            else:
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum(
                    "bhd,bshd->bhs", q, ck[:, :S_att]
                ) * (Dh**-0.5)
                visible = (
                    jnp.arange(S_att)[None, None, :]
                    <= positions[:, None, None]
                )
                att = jax.nn.softmax(
                    jnp.where(visible, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum(
                    "bhs,bshd->bhd", att, cv[:, :S_att]
                ).reshape(x.shape[0], H * Dh)
            ctx_new[op.name] = (ck, cv)
            return (
                o @ w[op.proj.kernel.path] + w[op.proj.bias.path]
            )

        return attn

    logits = _graph_replay(
        model, w, tok, attn_for,
        lambda a: _slice_seq_at_positions(a, positions, maxlen),
    )
    return logits, {
        name: ctx_new.get(name, caches[name]) for name in caches
    }


def prefill_forward(model, w, tokens_rows, caches, admit_mask, maxlen,
                    attention="naive"):
    """Full-sequence forward of a WAVE of (bucket-padded) prompts into
    their slots: every admitted slot's K/V for positions ``0..S-1``
    lands in its arena row in ONE pass — one program launch per
    admission wave per bucket, instead of one per request (prefill
    launches otherwise rival the decode itself on launch-bound
    backends).

    ``tokens_rows``: ``[num_slots, S]`` int32, ``S`` the bucket length
    (compiled once per bucket — the point of bucketing); rows of slots
    not being admitted carry padding and are masked off the write by
    ``admit_mask [num_slots]``. Positions past a real prompt hold
    padding whose K/V is garbage, but decode rewrites each such
    position before its cursor makes it visible, so no per-row length
    mask is needed.

    ``attention="flash"`` (ISSUE 11) runs the in-bucket causal core
    through the tiled online-softmax kernel with static future-tile
    skipping (:func:`elephas_tpu.ops.flash_serving.\
flash_causal_prefill`) — ~half the FLOPs and O(S·block) live score
    memory instead of the naive O(S²) matrix; float-tolerance parity,
    temp-0 token-exact.

    Returns ``(logits [num_slots, S, vocab], new_caches)``."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.ops.flash_serving import flash_causal_prefill

    ctx_new = {}
    S = int(tokens_rows.shape[1])

    def attn_for(op):
        def attn(x, *_a, **_k):
            ck, cv = caches[op.name]
            H, Dh = op.num_heads, op.head_dim
            B = x.shape[0]
            qkv = jnp.reshape(
                x @ w[op.qkv.kernel.path], (B, S, 3, H, Dh)
            )
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3,B,H,S,Dh]
            q, k, v = qkv[0], qkv[1], qkv[2]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos = jnp.asarray(cos_np)[None, None, :S]
                sin = jnp.asarray(sin_np)[None, None, :S]
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
            if attention == "flash":
                o = flash_causal_prefill(q, k, v, scale=Dh**-0.5)
            else:
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum(
                    "bhid,bhjd->bhij", q, k
                ) * (Dh**-0.5)
                causal = (
                    jnp.arange(S)[None, :] <= jnp.arange(S)[:, None]
                )[None, None]
                att = jax.nn.softmax(
                    jnp.where(causal, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum("bhij,bhjd->bhid", att, v)
            o = jnp.reshape(
                jnp.transpose(o, (0, 2, 1, 3)), (B, S, H * Dh)
            )
            # per-slot row write as a one-hot select (dynamic
            # scatter on the SHARDED slot axis would make GSPMD
            # emit collectives — same reasoning as the decode
            # cursor): [B, S, H, Dh] rows land where admitted
            k_rows = jnp.transpose(k, (0, 2, 1, 3))  # [B,S,H,Dh]
            v_rows = jnp.transpose(v, (0, 2, 1, 3))
            if S < maxlen:
                pad = ((0, 0), (0, maxlen - S), (0, 0), (0, 0))
                k_rows = jnp.pad(k_rows, pad)
                v_rows = jnp.pad(v_rows, pad)
            sel = (
                admit_mask[:, None]
                & (jnp.arange(maxlen) < S)[None, :]
            )[:, :, None, None]
            ck = jnp.where(sel, k_rows.astype(ck.dtype), ck)
            cv = jnp.where(sel, v_rows.astype(cv.dtype), cv)
            ctx_new[op.name] = (ck, cv)
            return (
                o @ w[op.proj.kernel.path] + w[op.proj.bias.path]
            )

        return attn

    logits = _graph_replay(
        model, w, tokens_rows, attn_for,
        lambda a: _slice_seq_prefix(a, S, maxlen),
    )
    return logits, {
        name: ctx_new.get(name, caches[name]) for name in caches
    }


def chunked_prefill_forward(model, w, tokens_chunk, caches, offsets,
                            chunk_lens, active, maxlen,
                            attention="naive", span=None):
    """Prefill a bounded CHUNK of each active slot's prompt, resuming
    from per-slot absolute offsets (ISSUE 4) — the program behind both
    chunked prefill (long prompts stream in ``prefill_chunk``-token
    slices between decode windows instead of stalling them) and
    suffix-only prefill after a prefix-cache copy.

    Unlike :func:`prefill_forward` (whole bucket, in-chunk causal
    attention, always from position 0), a chunk's queries must attend
    to K/V that already sits in the arena — rows written by the prefix
    copy and by earlier chunks — so attention here runs over the
    full cache row (masked to ``position <= query position``), after
    this chunk's own K/V rows land.

    ``tokens_chunk``: ``[num_slots, C]`` int32 — slot ``b``'s prompt
    tokens for absolute positions ``offsets[b] .. offsets[b]+C-1``,
    compiled once per chunk width ``C`` (a closed set: ONE width when
    ``prefill_chunk`` is fixed, suffix buckets from the scheduler
    ladder otherwise). ``chunk_lens[b] <= C`` masks a final partial
    chunk's padded tail off the cache write; ``active`` masks slots not
    prefilling this call. Padded/inactive lanes compute garbage that is
    never written and never read.

    ``attention``/``span`` (ISSUE 11): as in :func:`token_decode_step`
    — ``"flash"`` streams the updated arena row through the tiled
    online-softmax kernel, ``span`` (static, ``None`` = ``maxlen``)
    bounds the attended row to a span bucket covering every active
    slot's ``offsets + chunk_lens``.

    Returns ``(logits [num_slots, C, vocab], new_caches)`` — the
    caller samples a finalizing slot's first token from the logits row
    at its prompt-end chunk index.
    """
    import jax
    import jax.numpy as jnp

    from elephas_tpu.ops.flash_serving import flash_span_chunk

    S_att = int(maxlen if span is None else span)
    ctx_new = {}
    C = int(tokens_chunk.shape[1])
    # absolute positions of each slot's chunk rows, and the cache-write
    # select: chunk index i lands at cache row offsets[b]+i iff it is a
    # real (unpadded) token of an active slot — one-hot over the
    # sequence axis, slot-local under the mesh like the decode cursor
    pos_mat = offsets[:, None] + jnp.arange(C)[None, :]  # [B, C]
    valid = (
        active[:, None] & (jnp.arange(C)[None, :] < chunk_lens[:, None])
    )  # [B, C]
    write_sel = (
        pos_mat[:, None, :] == jnp.arange(maxlen)[None, :, None]
    ) & valid[:, None, :]  # [B, maxlen, C]

    def attn_for(op):
        def attn(x, *_a, **_k):
            ck, cv = caches[op.name]
            H, Dh = op.num_heads, op.head_dim
            B = x.shape[0]
            qkv = jnp.reshape(
                x @ w[op.qkv.kernel.path], (B, C, 3, H, Dh)
            )
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3,B,H,C,Dh]
            q, k, v = qkv[0], qkv[1], qkv[2]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos = _rows_at_position_matrix(
                    jnp.asarray(cos_np), pos_mat
                )[:, None]  # [B, 1, C, Dh]
                sin = _rows_at_position_matrix(
                    jnp.asarray(sin_np), pos_mat
                )[:, None]
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
            # land this chunk's K/V rows FIRST, then attend over the
            # updated arena row — queries see the prefix copy,
            # earlier chunks, and their own chunk's causal part
            k_rows = jnp.transpose(k, (0, 2, 1, 3))  # [B, C, H, Dh]
            v_rows = jnp.transpose(v, (0, 2, 1, 3))
            scat_k = jnp.einsum(
                "bsc,bchd->bshd", write_sel.astype(ck.dtype), k_rows
            )
            scat_v = jnp.einsum(
                "bsc,bchd->bshd", write_sel.astype(cv.dtype), v_rows
            )
            covered = jnp.any(write_sel, axis=2)[:, :, None, None]
            ck = jnp.where(covered, scat_k, ck)
            cv = jnp.where(covered, scat_v, cv)
            if attention == "flash":
                o = flash_span_chunk(
                    q, ck[:, :S_att], cv[:, :S_att], pos_mat,
                    scale=Dh**-0.5,
                )
            else:
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum(
                    "bhcd,bshd->bhcs", q, ck[:, :S_att]
                ) * (Dh**-0.5)
                visible = (
                    jnp.arange(S_att)[None, None, None, :]
                    <= pos_mat[:, None, :, None]
                )
                att = jax.nn.softmax(
                    jnp.where(visible, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum("bhcs,bshd->bhcd", att, cv[:, :S_att])
            o = jnp.reshape(
                jnp.transpose(o, (0, 2, 1, 3)), (B, C, H * Dh)
            )
            ctx_new[op.name] = (ck, cv)
            return (
                o @ w[op.proj.kernel.path] + w[op.proj.bias.path]
            )

        return attn

    logits = _graph_replay(
        model, w, tokens_chunk, attn_for,
        lambda a: _slice_seq_at_position_matrix(a, pos_mat, maxlen),
    )
    return logits, {
        name: ctx_new.get(name, caches[name]) for name in caches
    }


def verify_forward(model, w, tokens_window, caches, offsets, n_fed,
                   active, maxlen, attention="naive", span=None):
    """Batched K-token speculative VERIFY over the slot arena (ISSUE
    8): slot ``b`` feeds ``n_fed[b]`` tokens — its last sampled token
    followed by up to ``K-1`` drafted guesses — at absolute positions
    ``offsets[b] .. offsets[b]+n_fed[b]-1``, writes their K/V into its
    row, attends causally over the updated row, and returns a logits
    row per window position: row ``j`` scores the token at position
    ``offsets[b]+j+1``. The engine samples every row in one shot and
    accepts the longest draft prefix matching the model's own tokens
    plus one bonus token — at temperature 0 that prefix is BY
    CONSTRUCTION what sequential decode would have produced, so
    speculation never changes greedy output.

    This IS the chunked-prefill program with generated tokens in place
    of prompt tokens: chunk writes land first, queries attend over the
    updated arena row masked to ``position <= query position``, and a
    masked tail (``n_fed[b] < K``) neither writes nor matters — the
    delegation below is the whole point (one attention variant to keep
    bit-exact, one compiled shape per window width ``K``). The
    CURSOR-ROLLBACK contract lives host-side: rejected positions
    ``offsets[b]+a+1 ..`` hold garbage K/V after the call, and the
    engine simply rolls the slot's resident length back to
    ``offsets[b]+a+1`` — every garbage row is rewritten by a later
    feed before any query can see it (the same rewrite-before-visible
    invariant prefill padding already relies on)."""
    return chunked_prefill_forward(
        model, w, tokens_window, caches, offsets, n_fed, active, maxlen,
        attention=attention, span=span,
    )


def prefix_copy(caches, src_idx, copy_mask, copy_len, maxlen):
    """Slot-to-slot prefix transplant (ISSUE 4): destination slot ``d``
    (where ``copy_mask[d]``) receives donor slot ``src_idx[d]``'s first
    ``copy_len[d]`` K/V rows, for every layer — the device half of a
    prefix-cache hit. The admitted request then prefills only its
    un-cached suffix.

    ONE compiled shape total: every argument is a fixed ``[num_slots]``
    vector, so a wave with any mix of donors/destinations reuses the
    same program. The donor gather is a one-hot contraction over the
    slot axis — that axis is sharded over the mesh's batch axes, so
    this DOES lower to a collective, but it runs once per admission
    (outside the decode loop, where the same pattern was the measured
    ~15× hazard).

    Copied rows are bitwise what the destination's own prefill would
    have produced: causal attention makes position ``i``'s K/V a
    function of tokens ``0..i`` only, and the donor's rows were
    computed from those exact tokens.

    Returns the new caches dict."""
    import jax.numpy as jnp

    num_slots = src_idx.shape[0]
    onehot_src = (
        src_idx[:, None] == jnp.arange(num_slots)[None, :]
    ) & copy_mask[:, None]  # [dst, src]
    row_sel = (
        copy_mask[:, None]
        & (jnp.arange(maxlen)[None, :] < copy_len[:, None])
    )[:, :, None, None]  # [dst, maxlen, 1, 1]
    out = {}
    for name, (ck, cv) in caches.items():
        donor_k = jnp.einsum(
            "ab,bmhd->amhd", onehot_src.astype(ck.dtype), ck
        )
        donor_v = jnp.einsum(
            "ab,bmhd->amhd", onehot_src.astype(cv.dtype), cv
        )
        out[name] = (
            jnp.where(row_sel, donor_k, ck),
            jnp.where(row_sel, donor_v, cv),
        )
    return out
