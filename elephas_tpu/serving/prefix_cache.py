"""Host-side radix index over cached prompt prefixes (ISSUE 4).

Real serving fleets are dominated by requests sharing long prompt
prefixes (system prompts, few-shot templates). Once a prompt has been
prefilled into a KV slot, its first ``p`` arena rows are a reusable
artifact: causal attention means the K/V of position ``i`` depends only
on tokens ``0..i``, so ANY later prompt sharing those tokens can copy
the rows instead of recomputing them. This module is the index that
finds such donors.

Design constraints, in order:

- **Determinism.** Every gang process must compute the identical
  schedule from the identical submission order (the SPMD contract the
  scheduler already carries). So: no wall-clock anywhere — recency is a
  logical clock bumped per cache operation; ties break on slot id.
- **Slots are the unit of residence.** An entry maps one slot to the
  token sequence whose K/V occupies its first ``length`` rows. The trie
  gives longest-prefix lookup: each node holds the set of slots whose
  cached sequence passes THROUGH it, so a lookup walks the prompt until
  the path dies and takes the deepest node with a live slot.
- **Refcounts guard the admission wave.** ``lookup`` pins the donor it
  returns; an eviction scan skips pinned entries, so a donor chosen for
  one admission cannot be evicted (and re-leased) by a later admission
  in the same wave before the device copy has read it. Pins are
  released by the scheduler once the wave's copies are issued.
- **LRU eviction under slot pressure.** Donor slots (entries whose
  request finished) are reclaimable: when the free list is empty the
  scheduler evicts the least-recently-used unpinned, unleased entry and
  hands its slot to the next admission.

The cache never holds device memory itself — arena rows live in
:class:`~elephas_tpu.serving.kv_cache.SlotKVCache`; this is pure
bookkeeping about which rows are still meaningful.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from elephas_tpu import telemetry


@dataclass
class _Node:
    children: dict = field(default_factory=dict)  # token -> _Node
    slots: set = field(default_factory=set)  # slots covering this node


@dataclass
class CacheEntry:
    """One resident prefix: ``slot``'s first ``length`` arena rows hold
    the K/V of ``tokens``. ``leased`` while the prefilling request still
    occupies the slot (the rows are stable — decode writes at positions
    ``>= length`` — but the slot itself cannot be evicted); ``pins``
    counts admission-wave references that block eviction."""

    slot: int
    tokens: tuple
    length: int
    last_use: int
    leased: bool = True
    pins: int = 0


class PrefixCache:
    """Radix index of cached prompt prefixes over KV slots.

    All methods are O(len(tokens)) host work; nothing touches jax. The
    scheduler owns one instance when ``prefix_cache=True`` and drives
    it strictly from submission order.
    """

    def __init__(self):
        self._root = _Node()
        self._entries: dict[int, CacheEntry] = {}
        self._clock = 0
        # counters for stats()/bench (ISSUE 5): registry-backed, read
        # back through the properties below — one store, no drift. The
        # logical `_clock` above stays plain: it DRIVES eviction order
        # (control flow), which telemetry never may.
        reg = telemetry.registry()
        cid = telemetry.instance_label()
        self.telemetry_label = cid

        def _c(name, help_):
            return reg.counter(
                name, help_, labels=("cache",)
            ).labels(cache=cid)

        self._m_hits = _c(
            "elephas_prefix_cache_hits_total",
            "Admissions served a donor copy from the prefix cache",
        )
        self._m_misses = _c(
            "elephas_prefix_cache_misses_total",
            "Admissions that landed cold (no usable cached prefix)",
        )
        self._m_reused_tokens = _c(
            "elephas_prefix_cache_reused_tokens_total",
            "Prompt tokens served by donor copy instead of prefill",
        )
        self._m_evictions = _c(
            "elephas_prefix_cache_evictions_total",
            "Donor entries evicted under slot pressure (LRU)",
        )

    # registry-backed counter views (see __init__)

    @property
    def hits(self) -> int:
        return int(self._m_hits.value)

    @property
    def misses(self) -> int:
        return int(self._m_misses.value)

    @property
    def reused_tokens(self) -> int:
        return int(self._m_reused_tokens.value)

    @property
    def evictions(self) -> int:
        return int(self._m_evictions.value)

    def release_telemetry(self) -> None:
        """Retire this cache's labeled series from the process registry
        (cascaded from the owning scheduler/engine). The counter views
        above keep reading their own series."""
        telemetry.remove_series(cache=self.telemetry_label)

    # -- registration ---------------------------------------------------

    def insert(self, slot: int, tokens) -> None:
        """Register ``slot`` as holding the K/V of ``tokens`` (called
        when a request's prefill completes — the rows exist from that
        moment on). Replaces any previous entry for the slot."""
        if slot in self._entries:
            self.remove(slot)
        tokens = tuple(int(t) for t in tokens)
        self._clock += 1
        self._entries[slot] = CacheEntry(
            slot=slot, tokens=tokens, length=len(tokens),
            last_use=self._clock,
        )
        node = self._root
        for t in tokens:
            node = node.children.setdefault(t, _Node())
            node.slots.add(slot)

    def release(self, slot: int) -> bool:
        """The occupying request finished: the entry survives as an
        evictable donor. Returns True when the slot is retained (the
        scheduler then keeps it OFF the free list)."""
        entry = self._entries.get(slot)
        if entry is None:
            return False
        entry.leased = False
        return True

    def remove(self, slot: int) -> None:
        """Drop the slot's entry (it is being re-leased or evicted —
        its rows are about to be overwritten)."""
        entry = self._entries.pop(slot, None)
        if entry is None:
            return
        node, path = self._root, []
        for t in entry.tokens:
            child = node.children.get(t)
            if child is None:  # defensive: trie already pruned
                break
            path.append((node, t, child))
            child.slots.discard(slot)
            node = child
        # prune now-empty suffix nodes so the trie does not grow
        # unboundedly over the server's life
        for parent, t, child in reversed(path):
            if not child.slots and not child.children:
                del parent.children[t]

    # -- lookup / pinning ----------------------------------------------

    def match(self, prompt, max_reuse: int | None = None):
        """Longest cached prefix of ``prompt`` strictly shorter than
        the prompt (at least one suffix token must remain to prefill —
        the final position's logits are what admission samples from).

        PURE — no counter, recency, or pin mutation: ``admit()`` probes
        the queue head every step even when no slot is available, and a
        blocked request must not inflate hit stats or bump its donor's
        LRU rank once per step (that skewed eviction toward the blocked
        request's donor and made the published hit counts wrong under
        slot pressure). Callers :meth:`pin` the donor while they hold a
        reference across eviction decisions, then :meth:`commit_hit`
        (or :meth:`record_miss`) only when the admission really lands.

        Returns ``(slot, reuse_len)`` or ``(None, 0)``."""
        cap = len(prompt) - 1
        if max_reuse is not None:
            cap = min(cap, int(max_reuse))
        node, depth = self._root, 0
        best_depth, best_node = 0, None
        for t in prompt:
            if depth >= cap:
                break
            node = node.children.get(int(t))
            if node is None or not node.slots:
                break
            depth += 1
            best_depth, best_node = depth, node
        if best_node is None:
            return None, 0
        # deterministic choice: most recently used, slot id breaking
        # ties (every gang process computes the identical donor)
        slot = max(
            best_node.slots,
            key=lambda s: (self._entries[s].last_use, -s),
        )
        return slot, best_depth

    def match_len(self, prompt) -> int:
        """How many leading tokens of ``prompt`` an admission would
        reuse from this cache — the cache-warmth probe (ISSUE 12
        satellite, the ROADMAP fleet router's cache-aware-placement
        primitive). PURE like :meth:`match` (no hit/LRU/pin mutation,
        probe at any rate without skewing stats or eviction order) and
        by construction identical to ``match(prompt)[1]``, so a
        router's placement estimate can never disagree with what
        admission then does. Purity is not thread-safety: the trie is
        mutated by the thread driving admission, so serialize probes
        with it (the gateway's engine lock is that serialization)."""
        return self.match(prompt)[1]

    def pin(self, slot: int) -> None:
        """Block eviction of the entry while a wave holds it."""
        self._entries[slot].pins += 1

    def unpin(self, slot: int) -> None:
        entry = self._entries.get(slot)
        if entry is not None and entry.pins > 0:
            entry.pins -= 1

    def commit_hit(self, slot: int, reuse_len: int) -> None:
        """An admission actually reuses ``slot``'s rows: bump its
        recency and the hit accounting."""
        entry = self._entries.get(slot)
        if entry is not None:
            self._clock += 1
            entry.last_use = self._clock
        self._m_hits.inc()
        self._m_reused_tokens.inc(int(reuse_len))

    def record_miss(self) -> None:
        """An admission landed with no reuse (no match, or the
        cold-fallback path dropped its pinned donor)."""
        self._m_misses.inc()

    def flush(self) -> list[int]:
        """Drop EVERY entry (donors and leased alike) and return the
        slots that were resident as unleased donors — the caller owns
        putting those back on its free list. Used on weight refresh:
        cached rows were computed under the old weights, and a donor
        copy would silently splice stale K/V into a new-weights
        request."""
        donors = self.donor_slots
        for slot in list(self._entries):
            self.remove(slot)
        return donors

    # -- eviction -------------------------------------------------------

    def evict_lru(self) -> int | None:
        """Evict the least-recently-used unleased, unpinned entry and
        return its (now free) slot — or None when nothing is evictable.
        Ties break on slot id for gang determinism."""
        victims = [
            e for e in self._entries.values()
            if not e.leased and e.pins == 0
        ]
        if not victims:
            return None
        victim = min(victims, key=lambda e: (e.last_use, e.slot))
        self.remove(victim.slot)
        self._m_evictions.inc()
        return victim.slot

    # -- introspection --------------------------------------------------

    @property
    def donor_slots(self) -> list[int]:
        """Slots resident as unleased donors (sorted, deterministic)."""
        return sorted(
            s for s, e in self._entries.items() if not e.leased
        )

    def entry(self, slot: int) -> CacheEntry | None:
        return self._entries.get(slot)

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "donors": len(self.donor_slots),
            "hits": self.hits,
            "misses": self.misses,
            "reused_tokens": self.reused_tokens,
            "evictions": self.evictions,
        }


@dataclass
class BlockEntry:
    """One indexed full-block prompt prefix (paged mode, ISSUE 7):
    ``blocks[i]`` holds the K/V of ``tokens[i·bs : (i+1)·bs]``. The
    entry owns one allocator reference per block, independent of the
    request that prefilled them — the request can finish, be preempted,
    or free its table without invalidating the entry."""

    eid: int
    tokens: tuple
    blocks: tuple
    last_use: int


class PagedPrefixIndex:
    """Radix index over FULL-BLOCK prompt prefixes for the paged arena
    (ISSUE 7) — the block-refcount successor of :class:`PrefixCache`'s
    donor-slot scheme. Entries hold block-id lists instead of slots, so

    - a prefix hit is a COPY-FREE block-table splice: the shared blocks
      join the new request's table with one more reference each — no
      device copy program, no donor gather, and the "donor" never
      occupies a decode slot;
    - sharing is at full-block granularity only (a partially-filled
      block also holds the writer's later tokens, so splicing it would
      let the sharer read rows it must instead compute — the trailing
      ``len(prompt) % block_size`` tokens of a hit re-prefill with the
      suffix);
    - eviction under pool pressure (:meth:`evict_for`) drops LRU
      entries whose blocks would actually free (refcount 1) —
      releasing an entry shared with live tables frees nothing and is
      skipped;
    - under the PP engine (ISSUE 16) the same index spans the
      PER-STAGE pools CROSS-STAGE for free: the shared allocator
      leases one block id across all stages (every stage stores its
      layers' rows at that id in its own pool), so one spliced id
      skips the prefix's chunks on EVERY stage at once — block-id
      lists are mesh-layout-agnostic, which is why neither this index
      nor the allocator knows whether it serves a flat or a PP arena.

    Same determinism rules as :class:`PrefixCache`: logical clock
    recency, entry-id tie-breaks, :meth:`match` is PURE (commit happens
    only when the admission lands)."""

    def __init__(self, allocator):
        self._alloc = allocator
        self._root = _Node()  # node.slots holds entry ids here
        self._entries: dict[int, BlockEntry] = {}
        self._by_tokens: dict[tuple, BlockEntry] = {}
        self._clock = 0
        self._ids = itertools.count()
        reg = telemetry.registry()
        cid = telemetry.instance_label()
        self.telemetry_label = cid

        def _c(name, help_):
            return reg.counter(
                name, help_, labels=("cache",)
            ).labels(cache=cid)

        self._m_hits = _c(
            "elephas_prefix_cache_hits_total",
            "Admissions served a donor copy from the prefix cache",
        )
        self._m_misses = _c(
            "elephas_prefix_cache_misses_total",
            "Admissions that landed cold (no usable cached prefix)",
        )
        self._m_reused_tokens = _c(
            "elephas_prefix_cache_reused_tokens_total",
            "Prompt tokens served by donor copy instead of prefill",
        )
        self._m_evictions = _c(
            "elephas_prefix_cache_evictions_total",
            "Donor entries evicted under slot pressure (LRU)",
        )
        self._m_shared_blocks = _c(
            "elephas_prefix_blocks_shared_total",
            "Pool blocks spliced copy-free into admitted requests' "
            "block tables via prefix-index refcount sharing",
        )

    # registry-backed counter views (same contract as PrefixCache)

    @property
    def hits(self) -> int:
        return int(self._m_hits.value)

    @property
    def misses(self) -> int:
        return int(self._m_misses.value)

    @property
    def reused_tokens(self) -> int:
        return int(self._m_reused_tokens.value)

    @property
    def evictions(self) -> int:
        return int(self._m_evictions.value)

    @property
    def shared_blocks(self) -> int:
        return int(self._m_shared_blocks.value)

    def release_telemetry(self) -> None:
        telemetry.remove_series(cache=self.telemetry_label)

    # -- registration ---------------------------------------------------

    def insert(self, tokens, blocks) -> None:
        """Index the full-block prefix of ``tokens`` whose K/V lives in
        ``blocks`` (the owning request's leading table blocks), taking
        one allocator reference per indexed block. Called when a
        request's prefill completes — the rows exist and are final from
        that moment (decode writes only at positions ``>= len(prompt)``)
        — so sharing starts while the writer still decodes. An exact
        duplicate bumps recency instead of double-indexing."""
        bs = self._alloc.block_size
        n_full = len(tokens) // bs
        if n_full < 1:
            return
        key = tuple(int(t) for t in tokens[: n_full * bs])
        self._clock += 1
        prev = self._by_tokens.get(key)
        if prev is not None:
            prev.last_use = self._clock
            return
        held = tuple(int(b) for b in blocks[:n_full])
        if len(held) != n_full:
            raise ValueError(
                f"insert(): {n_full} full prompt blocks indexed but "
                f"only {len(held)} block ids supplied"
            )
        self._alloc.ref(held)
        entry = BlockEntry(
            eid=next(self._ids), tokens=key, blocks=held,
            last_use=self._clock,
        )
        self._entries[entry.eid] = entry
        self._by_tokens[key] = entry
        node = self._root
        for t in key:
            node = node.children.setdefault(t, _Node())
            node.slots.add(entry.eid)

    def _remove(self, entry: BlockEntry) -> list[int]:
        """Drop the entry, prune its trie path, release its block
        references. Returns the block ids that actually freed."""
        del self._entries[entry.eid]
        del self._by_tokens[entry.tokens]
        node, path = self._root, []
        for t in entry.tokens:
            child = node.children.get(t)
            if child is None:  # defensive: trie already pruned
                break
            path.append((node, t, child))
            child.slots.discard(entry.eid)
            node = child
        for parent, t, child in reversed(path):
            if not child.slots and not child.children:
                del parent.children[t]
        return self._alloc.deref(entry.blocks)

    # -- lookup / splice ------------------------------------------------

    def match(self, prompt):
        """Longest indexed FULL-BLOCK prefix of ``prompt`` strictly
        shorter than the prompt (at least one suffix token must remain
        to prefill). PURE — same contract as :meth:`PrefixCache.match`.

        Returns ``(eid, reuse_tokens)`` (``reuse_tokens`` a multiple of
        the block size) or ``(None, 0)``."""
        bs = self._alloc.block_size
        cap = len(prompt) - 1
        node, depth = self._root, 0
        best_node, best_depth = None, 0
        for t in prompt:
            if depth >= cap:
                break
            node = node.children.get(int(t))
            if node is None or not node.slots:
                break
            depth += 1
            if depth % bs == 0:
                # only full-block depths are spliceable: any entry
                # passing through this node covers >= depth tokens,
                # hence >= depth/bs whole blocks
                best_node, best_depth = node, depth
        if best_node is None:
            return None, 0
        eid = max(
            best_node.slots,
            key=lambda e: (self._entries[e].last_use, -e),
        )
        return eid, best_depth

    def match_len(self, prompt) -> int:
        """Reusable full-block prefix length for ``prompt`` — the pure
        cache-warmth probe (ISSUE 12 satellite), identical to
        ``match(prompt)[1]`` by construction; see
        :meth:`PrefixCache.match_len`."""
        return self.match(prompt)[1]

    def commit_hit(self, eid: int, reuse_len: int) -> list[int]:
        """The admission lands: reference the entry's first
        ``reuse_len / bs`` blocks for the new table and return their
        ids (in prompt order). Bumps recency + hit accounting."""
        entry = self._entries[eid]
        self._clock += 1
        entry.last_use = self._clock
        n = int(reuse_len) // self._alloc.block_size
        shared = list(entry.blocks[:n])
        self._alloc.ref(shared)
        self._m_hits.inc()
        self._m_reused_tokens.inc(int(reuse_len))
        self._m_shared_blocks.inc(n)
        return shared

    def record_miss(self) -> None:
        self._m_misses.inc()

    # -- eviction / flush -----------------------------------------------

    def evict_for(self, n_blocks: int) -> int:
        """Release LRU entries until at least ``n_blocks`` pool blocks
        freed or nothing more can free. Entries none of whose blocks
        would free (all still referenced by live tables or longer
        entries) are skipped — dropping them reclaims nothing and would
        only forget reusable prefixes. Returns blocks freed."""
        freed = 0
        while freed < n_blocks:
            victims = sorted(
                self._entries.values(),
                key=lambda e: (e.last_use, e.eid),
            )
            pick = next(
                (
                    e for e in victims
                    if any(
                        self._alloc.ref_count(b) == 1 for b in e.blocks
                    )
                ),
                None,
            )
            if pick is None:
                break
            freed += len(self._remove(pick))
            self._m_evictions.inc()
        return freed

    def flush(self) -> None:
        """Drop EVERY entry and release its block references (weight
        refresh: indexed rows were computed under the old weights — a
        splice would silently mix weight generations)."""
        for eid in list(self._entries):
            entry = self._entries.get(eid)
            if entry is not None:
                self._remove(entry)

    # -- introspection --------------------------------------------------

    def entry(self, eid: int) -> BlockEntry | None:
        return self._entries.get(eid)

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "indexed_blocks": sum(
                len(e.blocks) for e in self._entries.values()
            ),
            "hits": self.hits,
            "misses": self.misses,
            "reused_tokens": self.reused_tokens,
            "evictions": self.evictions,
            "shared_blocks": self.shared_blocks,
        }
