"""Iteration-level request scheduling (Orca-style continuous batching).

Pure host-side bookkeeping — no jax anywhere: the scheduler decides
*which* request occupies *which* slot at each engine step, and the
engine turns those decisions into fixed-shape device programs. Keeping
this layer free of device state is what makes it trivially SPMD-safe:
every gang process runs the identical deterministic schedule from the
identical submission order (the same contract ``generate()`` already
imposes).

Admission is greedy into free slots at every step boundary (requests
submitted mid-flight join the next step's admission wave — no
generation "epoch" to wait for), and slots reclaim the moment a
sequence hits EOS or its token budget, so the freed compute is re-used
by the very next waiting request instead of idling until the batch
drains. The admission ORDER is FIFO by default and pluggable through
an SLO policy (ISSUE 10, :mod:`elephas_tpu.serving.policy`): the
policy reorders the waiting queue before every admission attempt
(fair share / deadline EDF / aging) and supplies the effective
preemption priority — all host-side, all deterministic, so the gang
contract is untouched.

Prompt lengths are padded up to a fixed **bucket ladder**
(:func:`default_buckets`: powers of two, capped at the model's
``maxlen``) so the engine compiles one prefill program per bucket and
ONE decode program total — a small closed shape set, killing the
recompile churn the one-shot path's 16-entry jit cache only papers
over.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field

from elephas_tpu import telemetry
from elephas_tpu.serving.paged_kv import blocks_for
from elephas_tpu.serving.prefix_cache import PagedPrefixIndex, PrefixCache


# -- request-id minting (ISSUE 14 satellite) ---------------------------
# Each scheduler mints rids from its OWN stride of the integer line:
# the Nth scheduler constructed in this process starts at N * RID_STRIDE
# (process-monotonic, no pids, no wall time — the same determinism
# contract as telemetry.instance_label). Before this, every engine
# counted from 0, so rids COLLIDED across engines within one process —
# harmless for a single engine, a trace-reconstruction flake for test
# combos with several, and outright wrong for the fleet router, which
# keys in-flight requests, migration records, and re-drives by rid
# across replicas. The stride leaves ~10^12 rids per engine; a gang of
# processes running the identical construction + submission schedule
# still derives identical rids on every process.
RID_STRIDE = 1 << 40
_rid_bases = itertools.count()


def default_buckets(max_len: int, floor: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt buckets ``[floor, 2·floor, ..]`` capped at
    (and always including) ``max_len``."""
    if max_len <= 0:
        raise ValueError(f"max_len must be positive, got {max_len}")
    buckets = []
    b = max(1, floor)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def bucket_for(prompt_len: int, buckets) -> int:
    """Smallest bucket holding ``prompt_len`` tokens."""
    for b in buckets:
        if b >= prompt_len:
            return int(b)
    raise ValueError(
        f"prompt of {prompt_len} tokens exceeds the largest bucket "
        f"{max(buckets)}"
    )


@dataclass
class Request:
    """One in-flight generation request.

    ``tokens`` accumulates the GENERATED continuation only (the prompt
    is not repeated there); ``emitted`` marks how many of those the
    caller has already consumed via the streaming iterator.
    ``on_token(token, done)`` is an optional per-token consumer
    callback; when it raises, the engine fails THIS request (``error``
    set, slot reclaimed) and keeps serving the rest."""

    rid: int
    prompt: tuple
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: int | None = None
    # scheduling priority (paged preemption, ISSUE 7): an arriving
    # request may preempt active requests of STRICTLY lower priority
    # when the block pool is exhausted; equal priorities never preempt.
    # With a policy installed (ISSUE 10) the comparisons read the
    # policy's priority_of() instead — this field is the caller's base.
    priority: int = 0
    # SLO scheduling (ISSUE 10): the tenant this request accounts
    # under (None = the implicit default tenant) and its declared
    # time-to-first-token budget. The deadline orders the schedule as
    # a CLASS (tighter budget first — logical, gang-deterministic);
    # wall-clock attainment is measured in telemetry only.
    tenant: str | None = None
    ttft_deadline_ms: float | None = None
    tokens: list = field(default_factory=list)
    slot: int | None = None
    done: bool = False
    emitted: int = 0
    # logical submit stamp (ISSUE 12): the scheduler step count at
    # submit — queue-wait in the flight record is measured in STEPS
    # (admit_step - submit_step), never wall time, so every gang
    # process reconstructs the identical lifecycle
    submit_step: int | None = None
    submit_time: float | None = None
    finish_time: float | None = None
    on_token: object | None = None
    error: BaseException | None = None
    # latency accounting (ISSUE 4): host arrival time of each generated
    # token — token_times[0] - submit_time is the request's TTFT, the
    # consecutive deltas its inter-token latencies
    token_times: list = field(default_factory=list)
    # prompt tokens served from the prefix cache instead of prefill
    reused_tokens: int = 0
    # speculative decoding accounting (ISSUE 8): drafted tokens the
    # engine's verify forward scored for THIS request, and how many it
    # accepted — per-request views of the engine's registry counters
    # (the acceptance throttle reads its own windowed state, not these)
    spec_drafted: int = 0
    spec_accepted: int = 0
    # exemplar label set (ISSUE 12): built ONCE at submit and reused
    # for every TTFT/ITL observation of this request — the per-token
    # hot path must not allocate a dict + str per observation
    exemplar: dict | None = None

    @property
    def full_sequence(self) -> list:
        return list(self.prompt) + self.tokens

    @property
    def ttft(self) -> float | None:
        """Submit→first-token seconds (None until the first token)."""
        if not self.token_times or self.submit_time is None:
            return None
        return self.token_times[0] - self.submit_time

    @property
    def inter_token_times(self) -> list:
        """Deltas between consecutive token arrivals (seconds)."""
        tt = self.token_times
        return [b - a for a, b in zip(tt, tt[1:])]


@dataclass
class Admission:
    """One admission decision: ``req`` leases ``slot``; when the prefix
    cache found a donor, ``donor_slot``'s first ``reuse_len`` arena
    rows are copied before the (suffix-only) prefill.

    Paged mode (ISSUE 7) fills the second group instead: ``blocks`` is
    the slot's freshly-built block table (shared splice + own
    allocation), ``shared_len`` the copy-free prefix tokens already
    resident in the spliced blocks (prefill starts there), and
    ``resume`` the preemption record when this admission brings an
    offloaded request back (the engine restores its K/V and cursor
    instead of prefilling)."""

    req: Request
    slot: int
    donor_slot: int | None = None
    reuse_len: int = 0
    blocks: list | None = None
    shared_len: int = 0
    resume: "Preemption | None" = None
    # bubble-fill admission (ISSUE 16): the slot landed in a wave with
    # no decode-active occupants, so the PP engine prefills this
    # request through that wave's idle decode-window ticks instead of
    # dispatching a standalone prefill ring between windows
    fill: bool = False


@dataclass
class Preemption:
    """One preemption decision (paged mode): ``req`` lost ``slot``;
    its first ``len(blocks)`` table blocks hold K/V for positions
    ``0..cur_len-1`` and must be offloaded to host BEFORE any program
    writes the pool again (the engine enforces the ordering). The
    request re-queues at the waiting front and resumes bit-exact."""

    req: Request
    slot: int
    blocks: tuple
    cur_len: int


class Scheduler:
    """FIFO queue + slot lease tracking for :class:`InferenceEngine`."""

    def __init__(self, num_slots: int, buckets, prefix_cache: bool = False,
                 prefix_min_reuse: int = 1, allocator=None,
                 preemption: bool = False, policy=None,
                 wave_slots: int | None = None):
        self.num_slots = int(num_slots)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        # wave-aware admission (ISSUE 15): the PP engine partitions
        # the arena statically into waves of `wave_slots` slots (slot
        # i -> wave i // wave_slots); admission then picks the free
        # slot whose wave holds the FEWEST active requests (ties: the
        # lowest wave, then the lowest slot — fully deterministic, so
        # the gang contract is untouched). An unevenly-filled wave is
        # a pipeline tick doing less work while another wave's slots
        # queue, so balance is throughput, not taste. None keeps the
        # legacy lowest-free-slot order byte-for-byte.
        if wave_slots is not None:
            wave_slots = int(wave_slots)
            if wave_slots < 1 or self.num_slots % wave_slots:
                raise ValueError(
                    f"wave_slots={wave_slots} must be a positive "
                    f"divisor of num_slots={self.num_slots}"
                )
        self.wave_slots = wave_slots
        self.waiting: deque[Request] = deque()
        self.active: dict[int, Request] = {}
        self._free: list[int] = list(range(self.num_slots))
        # rid base: this scheduler's own stride of the integer line —
        # see RID_STRIDE above (rid uniqueness across engines is
        # load-bearing for the fleet router)
        self.rid_base = next(_rid_bases) * RID_STRIDE
        self._ids = itertools.count(self.rid_base)
        # SLO admission policy (ISSUE 10): None keeps the bare-FIFO
        # fast path byte-for-byte; a policy gets the reorder/accounting
        # hooks documented in serving.policy
        self.policy = policy
        # outstanding token debt of the waiting queue (prompt +
        # remaining budget, summed) — the policy's admission-control
        # input, maintained incrementally at every enqueue/dequeue
        self.queued_tokens = 0
        # paged mode (ISSUE 7): an allocator switches admission from
        # slot-only leasing to slot+block leasing; the prefix cache
        # becomes a block-refcount index (copy-free splices) instead of
        # the donor-slot scheme
        self.allocator = allocator
        self.preemption = bool(preemption)
        if preemption and allocator is None:
            raise ValueError(
                "preemption requires the paged allocator — the fixed "
                "arena has no blocks to swap out"
            )
        self.tables: dict[int, list[int]] = {}
        # bumped on ANY table mutation so the engine can cheaply
        # invalidate its staged device copy of the block tables
        self.tables_version = 0
        self._preempted: dict[int, Preemption] = {}
        self.prefix_index = (
            PagedPrefixIndex(allocator)
            if prefix_cache and allocator is not None else None
        )
        self.prefix_cache = (
            PrefixCache()
            if prefix_cache and allocator is None else None
        )
        # matches shallower than this admit COLD: a 1-2 token
        # coincidental prefix is not worth a copy dispatch (and on
        # accidental-hit traffic would drag every admission through
        # the donor path)
        self.prefix_min_reuse = max(1, int(prefix_min_reuse))
        # occupancy accounting for the serving bench — plain ints, the
        # engine reads them for round-scoped occupancy math
        self._steps = 0
        self._busy_slot_steps = 0
        # telemetry (ISSUE 5): admission counters by kind + a queue-
        # depth gauge, report-only (the schedule itself never reads
        # them — gang determinism is untouched)
        reg = telemetry.registry()
        sid = telemetry.instance_label()
        self.telemetry_label = sid
        admissions = reg.counter(
            "elephas_serving_admissions_total",
            "Requests admitted into KV slots, by admission kind",
            labels=("scheduler", "kind"),
        )
        self._m_admit_cold = admissions.labels(scheduler=sid, kind="cold")
        self._m_admit_hit = admissions.labels(
            scheduler=sid, kind="prefix_hit"
        )
        self._m_admit_resume = admissions.labels(
            scheduler=sid, kind="resume"
        )
        self._m_waiting = reg.gauge(
            "elephas_serving_waiting_requests",
            "Requests queued behind a full slot arena",
            labels=("scheduler",),
        ).labels(scheduler=sid)

    def release_telemetry(self) -> None:
        """Retire this scheduler's labeled series (and its prefix
        cache's, if any) from the process registry — the engine's
        ``release_telemetry()`` cascades here. Explicit-only; see
        ``Registry.remove_series``."""
        telemetry.remove_series(scheduler=self.telemetry_label)
        if self.prefix_cache is not None:
            self.prefix_cache.release_telemetry()
        if self.prefix_index is not None:
            self.prefix_index.release_telemetry()

    # -- submission ----------------------------------------------------

    @staticmethod
    def _debt(req: Request) -> int:
        """Tokens this request still owes the engine (prompt +
        remaining budget) — frozen while it waits, so enqueue/dequeue
        adjustments are exactly symmetric."""
        return len(req.prompt) + req.max_new_tokens - len(req.tokens)

    def submit(self, request: Request) -> Request:
        request.rid = next(self._ids) if request.rid is None else request.rid
        self.waiting.append(request)
        self.queued_tokens += self._debt(request)
        if self.policy is not None:
            self.policy.on_submit(request)
        self._m_waiting.set(len(self.waiting))
        return request

    def make_request(self, prompt, max_new_tokens, temperature=0.0,
                     eos_id=None, on_token=None,
                     priority: int = 0, tenant: str | None = None,
                     ttft_deadline_ms: float | None = None) -> Request:
        return Request(
            rid=next(self._ids),
            prompt=tuple(int(t) for t in prompt),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            eos_id=None if eos_id is None else int(eos_id),
            on_token=on_token,
            priority=int(priority),
            tenant=tenant,
            ttft_deadline_ms=(
                None if ttft_deadline_ms is None else float(ttft_deadline_ms)
            ),
        )

    def remove_waiting(self, rid: int) -> Request | None:
        """Pull one request out of the waiting queue by rid (cancel /
        migration export): drops its token debt and any preemption
        record; the caller owns the request — and its policy
        accounting — from here. None when the rid is not waiting."""
        req = next((r for r in self.waiting if r.rid == rid), None)
        if req is None:
            return None
        self.waiting.remove(req)
        self.queued_tokens -= self._debt(req)
        self._preempted.pop(rid, None)
        self._m_waiting.set(len(self.waiting))
        return req

    def adopt_preempted(self, req: Request, cur_len: int) -> None:
        """Enqueue a request whose K/V the engine holds as a host
        offload record (cross-replica migration import, ISSUE 14): it
        waits at the FRONT like a locally-preempted victim and resumes
        through the exact admission path preemption already uses —
        ``admit_paged`` sees the preemption record and plans a resume
        instead of a prefill."""
        self._preempted[req.rid] = Preemption(
            req=req, slot=-1, blocks=(), cur_len=int(cur_len),
        )
        self.waiting.appendleft(req)
        self.queued_tokens += self._debt(req)
        if self.policy is not None:
            self.policy.on_submit(req)
        self._m_waiting.set(len(self.waiting))

    def waiting_count(self, tenant: str) -> int:
        """Waiting requests accounted under ``tenant`` (the per-tenant
        queue-depth gauges read this live — no cached copy to drift)."""
        from elephas_tpu.serving.policy import DEFAULT_TENANT

        return sum(
            1 for r in self.waiting
            if (r.tenant if r.tenant is not None else DEFAULT_TENANT)
            == tenant
        )

    def queued_tokens_for(self, tenant: str | None) -> int:
        """The waiting queue's token debt owed by ONE tenant — the
        policy's per-tenant admission-control input. Computed live
        over the (small) queue rather than cached: one truth, no
        incremental-bookkeeping drift."""
        from elephas_tpu.serving.policy import DEFAULT_TENANT

        t = DEFAULT_TENANT if tenant is None else tenant
        return sum(
            self._debt(r) for r in self.waiting
            if (r.tenant if r.tenant is not None else DEFAULT_TENANT)
            == t
        )

    def _prio(self, req: Request) -> int:
        """Preemption-effective priority: the policy's view when one
        is installed (ISSUE 10 — deadline traffic may outrank
        best-effort), the caller's submit(priority=) otherwise."""
        if self.policy is not None:
            return self.policy.priority_of(req)
        return req.priority

    def _policy_reorder(self) -> None:
        """Let the policy re-rank the waiting queue before an
        admission attempt; preempted requests stay pinned at the
        front (their host-offloaded K/V resumes as soon as space
        frees)."""
        if self.policy is not None:
            self.policy.reorder(self.waiting, self._preempted)

    def _pop_free_slot(self) -> int:
        """Take one slot off the free list: lowest-first by default;
        wave-aware under ``wave_slots`` (see ``__init__``) — the free
        slot in the least-loaded wave, ties to the lowest slot."""
        if self.wave_slots is None:
            return self._free.pop(0)
        ws = self.wave_slots
        load = [0] * (self.num_slots // ws)
        for slot in self.active:
            load[slot // ws] += 1
        best = min(self._free, key=lambda s: (load[s // ws], s))
        self._free.remove(best)
        return best

    def _dequeue_head(self) -> Request:
        """Pop the queue head into an admission: debt drops and the
        policy charges the prefill (a resume re-admission charges
        nothing — its prompt was already served once)."""
        req = self.waiting.popleft()
        self.queued_tokens -= self._debt(req)
        if self.policy is not None:
            self.policy.on_admit(
                req, resumed=req.rid in self._preempted
            )
        return req

    # -- per-step decisions --------------------------------------------

    def admit(self) -> list[Admission]:
        """Lease slots to waiting requests (FIFO), lowest free slot
        first, evicting LRU prefix-cache donors under slot pressure —
        all deterministic for the SPMD contract. Returns the wave's
        :class:`Admission` plan (donor + reuse length resolved per
        request); the engine executes the copies and prefills.

        Donor pinning: a donor chosen for one admission is pinned so a
        LATER admission in the same wave cannot evict (and overwrite)
        it before the engine's copy program has read it. When the only
        evictable slot IS the pinned donor, reuse is dropped for that
        request (admitted cold into the evicted donor) — admission
        progress beats prefix reuse, and stalling here would livelock a
        one-slot engine whose sole donor matches the queue head."""
        admitted: list[Admission] = []
        pinned: list[int] = []
        cache = self.prefix_cache
        if self.policy is not None:
            self.policy.begin_wave()
        while self.waiting:
            # re-rank before EVERY attempt: an admission earlier in
            # this wave charged its tenant's counter, and the next
            # head must reflect that (otherwise one wave would drain
            # a whole tenant before fairness reacts)
            self._policy_reorder()
            req = self.waiting[0]
            donor, reuse = (None, 0)
            if cache is not None:
                # match() is PURE; hit/LRU accounting commits only if
                # the admission lands (a blocked queue head is probed
                # every step and must not skew stats or eviction order)
                donor, reuse = cache.match(req.prompt)
                if donor is not None and reuse < self.prefix_min_reuse:
                    donor, reuse = None, 0  # too shallow to pay a copy
                if donor is not None:
                    cache.pin(donor)
                    pinned.append(donor)
            if self._free:
                slot = self._pop_free_slot()
            else:
                slot = cache.evict_lru() if cache is not None else None
                if slot is None and donor is not None:
                    # the pinned donor may be the only evictable slot:
                    # fall back to a cold admission
                    cache.unpin(donor)
                    pinned.pop()
                    donor, reuse = None, 0
                    slot = cache.evict_lru()
                if slot is None:
                    break  # genuinely full — request keeps waiting
            self._dequeue_head()
            if cache is not None:
                cache.remove(slot)  # rows are about to be overwritten
                if donor is not None:
                    cache.commit_hit(donor, reuse)
                else:
                    cache.record_miss()
            req.slot = slot
            req.reused_tokens = reuse
            self.active[slot] = req
            (self._m_admit_hit if donor is not None
             else self._m_admit_cold).inc()
            admitted.append(
                Admission(req=req, slot=slot, donor_slot=donor,
                          reuse_len=reuse)
            )
        # the engine copies donor rows synchronously right after this
        # wave returns and nothing can evict before the next admit()
        # call, so wave-scoped pins release here
        if cache is not None:
            for slot in pinned:
                cache.unpin(slot)
        self._m_waiting.set(len(self.waiting))
        return admitted

    # -- paged admission (ISSUE 7) --------------------------------------

    def blocks_needed(self, req: Request) -> int:
        """Full reservation of ``req``: blocks covering prompt + the
        whole token budget. Reserving up front (vLLM reserves lazily
        and swaps on OOM) keeps the schedule gang-deterministic and
        means an admitted request can NEVER hit mid-flight pool
        exhaustion — preemption happens only at admission boundaries."""
        return blocks_for(
            len(req.prompt) + req.max_new_tokens,
            self.allocator.block_size,
        )

    def admit_paged(self, prefilling=frozenset(), bubble_fill: bool = False,
                    fill_budget: int | None = None):
        """Paged admission wave: FIFO head-blocking like :meth:`admit`,
        but a request needs BOTH a free slot and its full block
        reservation. Shortfalls resolve in deterministic order: evict
        LRU prefix-index entries first (cheap — they free whole blocks
        nobody is decoding with), then, when ``preemption`` is on and
        the head outranks an active request, preempt victims (lowest
        priority first, youngest first within a priority) until the
        head fits — or not at all, if even preempting every eligible
        victim would not admit it (no thrash for nothing). ``prefilling``
        slots are never victims (their tables are mid-write).

        Bubble-fill (ISSUE 16, PP engine only): with ``bubble_fill``
        on, a FRESH admission whose wave-aware slot lands in a wave
        with NO decode-active occupant — while at least one decode-
        active wave exists elsewhere to open windows — is flagged
        ``Admission.fill``: the engine prefills it through that wave's
        idle decode-window ticks instead of a standalone prefill ring.
        ``fill_budget`` caps concurrent fill slots (None = one wave's
        worth is the engine's practical bound). ``prefilling`` doubles
        as the current filler set: its members count as NON-decode
        occupants for the wave test and are never preemption victims.
        Resumes are never flagged (their K/V is already resident —
        there is nothing to prefill). With ``bubble_fill`` False the
        admission plan is byte-identical to PR 15.

        Returns ``(admissions, preemptions)``; the engine MUST offload
        every preemption's blocks before running any pool-writing
        program, then execute the admissions."""
        if self.allocator is None:
            raise RuntimeError("admit_paged() on a non-paged scheduler")
        admitted: list[Admission] = []
        preempts: list[Preemption] = []
        # fillers seen by the wave test: the engine's current fill
        # slots plus any admission THIS wave already flagged
        fillers: set[int] = set(prefilling)
        # rids admitted by THIS wave — never preemption victims within
        # it (their Admission is already in the returned plan; see
        # _plan_preemption)
        wave_rids: set[int] = set()
        alloc, idx = self.allocator, self.prefix_index
        if self.policy is not None:
            self.policy.begin_wave()
        while self.waiting:
            self._policy_reorder()
            req = self.waiting[0]
            need_total = self.blocks_needed(req)
            record = self._preempted.get(req.rid)
            eid, reuse = None, 0
            if record is None and idx is not None:
                # PURE probe; commit only when the admission lands
                eid, reuse = idx.match(req.prompt)
                if eid is not None and reuse < self.prefix_min_reuse:
                    eid, reuse = None, 0
            own_need = need_total - reuse // alloc.block_size
            short = own_need - alloc.free_count
            if short > 0 and idx is not None:
                idx.evict_for(short)
                short = own_need - alloc.free_count
            plan = []
            if short > 0 or not self._free:
                if self.preemption:
                    plan = self._plan_preemption(
                        req, short, bool(self._free), prefilling,
                        wave_rids,
                    )
                if not plan:
                    break  # head keeps waiting; nothing may jump it
            # the head WILL admit: remove it from the queue BEFORE
            # executing preemptions, so victims re-queue at the front
            # of the REMAINING queue (not ahead of the head — that
            # would make the wave pop the victim instead)
            self._dequeue_head()
            for victim in plan:
                preempts.append(self._preempt(victim))
            shared: list[int] = []
            if eid is not None:
                shared = idx.commit_hit(eid, reuse)
            elif idx is not None and record is None:
                idx.record_miss()
            own = alloc.alloc(own_need)
            assert own is not None  # guaranteed by the short check
            slot = self._pop_free_slot()
            fill = False
            if (bubble_fill and self.wave_slots is not None
                    and record is None):
                ws = self.wave_slots
                decode_slots = [
                    s for s in self.active if s not in fillers
                ]
                wave_has_decode = any(
                    s // ws == slot // ws for s in decode_slots
                )
                budget_ok = (
                    fill_budget is None or len(fillers) < int(fill_budget)
                )
                # fillable only when some OTHER wave is decoding —
                # without a decode-active wave no window would ever
                # run, and the filler would starve
                if decode_slots and not wave_has_decode and budget_ok:
                    fill = True
                    fillers.add(slot)
            self.tables[slot] = shared + own
            self.tables_version += 1
            req.slot = slot
            self.active[slot] = req
            wave_rids.add(req.rid)
            if record is not None:
                self._preempted.pop(req.rid)
                self._m_admit_resume.inc()
                admitted.append(Admission(
                    req=req, slot=slot, blocks=self.tables[slot],
                    resume=record,
                ))
            else:
                req.reused_tokens = reuse
                (self._m_admit_hit if eid is not None
                 else self._m_admit_cold).inc()
                admitted.append(Admission(
                    req=req, slot=slot, blocks=self.tables[slot],
                    shared_len=reuse, fill=fill,
                ))
        self._m_waiting.set(len(self.waiting))
        return admitted, preempts

    def _plan_preemption(self, req: Request, short: int,
                         have_slot: bool, prefilling, wave_rids):
        """Choose victims that would admit ``req`` — or none at all.
        Eligible: active, strictly lower priority, NOT mid-prefill,
        NOT admitted by this same wave (``wave_rids``: their Admission
        is already in the returned plan, so preempting them would
        double-lease their blocks — and for a RESUME admission, pop
        the engine's one offload record twice), and holding at least
        one generated token — a request with no token yet has no
        resident state an offload could represent (its prefill has
        not finalized). The token guard alone used to stand in for
        the same-wave rule, but a resume admitted earlier in the wave
        HAS tokens, which is exactly how a policy-boosted head
        exposed the hole. Order: lowest priority first, then youngest
        (largest rid) — the oldest work at each priority is preserved
        longest. Only blocks whose last reference is the victim's
        table count as freed (prefix-shared blocks survive via their
        index entry)."""
        head_prio = self._prio(req)
        cands = [
            r for slot, r in self.active.items()
            if self._prio(r) < head_prio and slot not in prefilling
            and r.tokens and r.rid not in wave_rids
        ]
        cands.sort(key=lambda r: (self._prio(r), -r.rid))
        chosen, freed, slots_freed = [], 0, 0
        for r in cands:
            if freed >= short and (have_slot or slots_freed > 0):
                break
            freed += sum(
                1 for b in self.tables[r.slot]
                if self.allocator.ref_count(b) == 1
            )
            slots_freed += 1
            chosen.append(r)
        if freed < short or not (have_slot or slots_freed > 0):
            return []
        return chosen

    def _preempt(self, req: Request) -> Preemption:
        """Bookkeeping half of a preemption: snapshot the offloadable
        blocks, free slot + block references, re-queue the victim at
        the waiting FRONT (it resumes as soon as space frees). The
        engine performs the actual host offload from the snapshot —
        the device rows stay intact until the next pool write."""
        slot = req.slot
        table = self.tables.pop(slot)
        self.tables_version += 1
        # resident K/V covers prompt + all generated tokens except the
        # last sampled one (its K/V lands on the next decode step)
        cur_len = len(req.prompt) + len(req.tokens) - 1
        rec = Preemption(
            req=req, slot=slot,
            blocks=tuple(table[: blocks_for(
                cur_len, self.allocator.block_size
            )]),
            cur_len=cur_len,
        )
        self.active.pop(slot)
        req.slot = None
        self._free.append(slot)
        self._free.sort()
        self.allocator.deref(table)
        self._preempted[req.rid] = rec
        self.waiting.appendleft(req)
        # back on the queue, back in the debt — _debt() deliberately
        # re-counts the prompt: the victim's claim on future capacity
        # includes re-residency for its prompt blocks, not just the
        # remaining budget (already-generated tokens are the only part
        # that never comes back); on_preempt (not on_submit) tells the
        # policy: re-arm aging, no counter lift
        self.queued_tokens += self._debt(req)
        if self.policy is not None:
            self.policy.on_preempt(req)
        return rec

    def on_prefill_complete(self, req: Request) -> None:
        """Register the request's prompt rows as a reusable prefix (its
        slot's first ``len(prompt)`` rows now hold that K/V). Paged
        mode indexes the prompt's FULL blocks by refcount instead."""
        if req.slot is None:
            return
        if self.prefix_index is not None:
            n_full = len(req.prompt) // self.allocator.block_size
            if n_full:
                self.prefix_index.insert(
                    req.prompt, self.tables[req.slot][:n_full]
                )
        elif self.prefix_cache is not None:
            self.prefix_cache.insert(req.slot, req.prompt)

    def flush_prefix_cache(self) -> None:
        """Invalidate every cached prefix and return donor slots to the
        free list (weight refresh: resident rows are stale). Paged mode
        releases the index's block references instead — donors never
        occupied slots there."""
        if self.prefix_index is not None:
            self.prefix_index.flush()
            return
        if self.prefix_cache is None:
            return
        self._free.extend(self.prefix_cache.flush())
        self._free.sort()

    def on_token(self, slot: int, token: int) -> bool:
        """Record one generated token for the slot's occupant; returns
        True when the request just finished (EOS or budget) — the
        caller then reclaims the slot."""
        req = self.active[slot]
        req.tokens.append(int(token))
        if (
            req.eos_id is not None and int(token) == req.eos_id
        ) or len(req.tokens) >= req.max_new_tokens:
            req.done = True
            return True
        return False

    def reclaim(self, slot: int) -> Request:
        """Free the slot immediately — the next :meth:`admit` can hand
        it to a waiting request in the same engine step. With the
        prefix cache on, a slot whose prompt rows are indexed is
        RETAINED as a donor instead (evicted LRU under pressure)."""
        req = self.active.pop(slot)
        req.slot = None
        if self.allocator is not None:
            # paged: the slot ALWAYS frees (donors never occupy one);
            # the table's block references drop, and any blocks the
            # prefix index holds (inserted at prefill completion)
            # survive on the index's own references
            table = self.tables.pop(slot, None)
            if table is not None:
                self.allocator.deref(table)
                self.tables_version += 1
        elif (
            self.prefix_cache is not None
            and self.prefix_cache.release(slot)
        ):
            return req  # resident donor — off the free list
        self._free.append(slot)
        self._free.sort()
        return req

    def note_step(self) -> None:
        self._steps += 1
        self._busy_slot_steps += len(self.active)

    # -- introspection -------------------------------------------------

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    @property
    def occupancy(self) -> float:
        """Mean busy-slot fraction over all decode steps so far."""
        if self._steps == 0:
            return 0.0
        return self._busy_slot_steps / (self._steps * self.num_slots)

    def queue_snapshot(self) -> list[dict]:
        """The waiting queue as structured rows (ISSUE 12 — the
        ``GET /debug/engine`` snapshot's queue section): rid, tenant,
        outstanding token debt, priority, deadline class, and whether
        the entry is a preempted request awaiting resume. Read-only
        host work; order is the queue's current (policy-ranked)
        order."""
        return [
            {
                "rid": r.rid,
                "tenant": r.tenant,
                "priority": r.priority,
                "debt_tokens": self._debt(r),
                "prompt_tokens": len(r.prompt),
                "ttft_deadline_ms": r.ttft_deadline_ms,
                "preempted": r.rid in self._preempted,
            }
            for r in self.waiting
        ]

    def bucket_for(self, prompt_len: int) -> int:
        return bucket_for(prompt_len, self.buckets)
