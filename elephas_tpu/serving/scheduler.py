"""Iteration-level request scheduling (Orca-style continuous batching).

Pure host-side bookkeeping — no jax anywhere: the scheduler decides
*which* request occupies *which* slot at each engine step, and the
engine turns those decisions into fixed-shape device programs. Keeping
this layer free of device state is what makes it trivially SPMD-safe:
every gang process runs the identical deterministic schedule from the
identical submission order (the same contract ``generate()`` already
imposes).

Admission is greedy FIFO into free slots at every step boundary
(requests submitted mid-flight join the next step's admission wave —
no generation "epoch" to wait for), and slots reclaim the moment a
sequence hits EOS or its token budget, so the freed compute is re-used
by the very next waiting request instead of idling until the batch
drains.

Prompt lengths are padded up to a fixed **bucket ladder**
(:func:`default_buckets`: powers of two, capped at the model's
``maxlen``) so the engine compiles one prefill program per bucket and
ONE decode program total — a small closed shape set, killing the
recompile churn the one-shot path's 16-entry jit cache only papers
over.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field

from elephas_tpu import telemetry
from elephas_tpu.serving.prefix_cache import PrefixCache


def default_buckets(max_len: int, floor: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt buckets ``[floor, 2·floor, ..]`` capped at
    (and always including) ``max_len``."""
    if max_len <= 0:
        raise ValueError(f"max_len must be positive, got {max_len}")
    buckets = []
    b = max(1, floor)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def bucket_for(prompt_len: int, buckets) -> int:
    """Smallest bucket holding ``prompt_len`` tokens."""
    for b in buckets:
        if b >= prompt_len:
            return int(b)
    raise ValueError(
        f"prompt of {prompt_len} tokens exceeds the largest bucket "
        f"{max(buckets)}"
    )


@dataclass
class Request:
    """One in-flight generation request.

    ``tokens`` accumulates the GENERATED continuation only (the prompt
    is not repeated there); ``emitted`` marks how many of those the
    caller has already consumed via the streaming iterator.
    ``on_token(token, done)`` is an optional per-token consumer
    callback; when it raises, the engine fails THIS request (``error``
    set, slot reclaimed) and keeps serving the rest."""

    rid: int
    prompt: tuple
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: int | None = None
    tokens: list = field(default_factory=list)
    slot: int | None = None
    done: bool = False
    emitted: int = 0
    submit_time: float | None = None
    finish_time: float | None = None
    on_token: object | None = None
    error: BaseException | None = None
    # latency accounting (ISSUE 4): host arrival time of each generated
    # token — token_times[0] - submit_time is the request's TTFT, the
    # consecutive deltas its inter-token latencies
    token_times: list = field(default_factory=list)
    # prompt tokens served from the prefix cache instead of prefill
    reused_tokens: int = 0

    @property
    def full_sequence(self) -> list:
        return list(self.prompt) + self.tokens

    @property
    def ttft(self) -> float | None:
        """Submit→first-token seconds (None until the first token)."""
        if not self.token_times or self.submit_time is None:
            return None
        return self.token_times[0] - self.submit_time

    @property
    def inter_token_times(self) -> list:
        """Deltas between consecutive token arrivals (seconds)."""
        tt = self.token_times
        return [b - a for a, b in zip(tt, tt[1:])]


@dataclass
class Admission:
    """One admission decision: ``req`` leases ``slot``; when the prefix
    cache found a donor, ``donor_slot``'s first ``reuse_len`` arena
    rows are copied before the (suffix-only) prefill."""

    req: Request
    slot: int
    donor_slot: int | None = None
    reuse_len: int = 0


class Scheduler:
    """FIFO queue + slot lease tracking for :class:`InferenceEngine`."""

    def __init__(self, num_slots: int, buckets, prefix_cache: bool = False,
                 prefix_min_reuse: int = 1):
        self.num_slots = int(num_slots)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.waiting: deque[Request] = deque()
        self.active: dict[int, Request] = {}
        self._free: list[int] = list(range(self.num_slots))
        self._ids = itertools.count()
        self.prefix_cache = PrefixCache() if prefix_cache else None
        # matches shallower than this admit COLD: a 1-2 token
        # coincidental prefix is not worth a copy dispatch (and on
        # accidental-hit traffic would drag every admission through
        # the donor path)
        self.prefix_min_reuse = max(1, int(prefix_min_reuse))
        # occupancy accounting for the serving bench — plain ints, the
        # engine reads them for round-scoped occupancy math
        self._steps = 0
        self._busy_slot_steps = 0
        # telemetry (ISSUE 5): admission counters by kind + a queue-
        # depth gauge, report-only (the schedule itself never reads
        # them — gang determinism is untouched)
        reg = telemetry.registry()
        sid = telemetry.instance_label()
        self.telemetry_label = sid
        admissions = reg.counter(
            "elephas_serving_admissions_total",
            "Requests admitted into KV slots, by admission kind",
            labels=("scheduler", "kind"),
        )
        self._m_admit_cold = admissions.labels(scheduler=sid, kind="cold")
        self._m_admit_hit = admissions.labels(
            scheduler=sid, kind="prefix_hit"
        )
        self._m_waiting = reg.gauge(
            "elephas_serving_waiting_requests",
            "Requests queued behind a full slot arena",
            labels=("scheduler",),
        ).labels(scheduler=sid)

    def release_telemetry(self) -> None:
        """Retire this scheduler's labeled series (and its prefix
        cache's, if any) from the process registry — the engine's
        ``release_telemetry()`` cascades here. Explicit-only; see
        ``Registry.remove_series``."""
        telemetry.remove_series(scheduler=self.telemetry_label)
        if self.prefix_cache is not None:
            self.prefix_cache.release_telemetry()

    # -- submission ----------------------------------------------------

    def submit(self, request: Request) -> Request:
        request.rid = next(self._ids) if request.rid is None else request.rid
        self.waiting.append(request)
        self._m_waiting.set(len(self.waiting))
        return request

    def make_request(self, prompt, max_new_tokens, temperature=0.0,
                     eos_id=None, on_token=None) -> Request:
        return Request(
            rid=next(self._ids),
            prompt=tuple(int(t) for t in prompt),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            eos_id=None if eos_id is None else int(eos_id),
            on_token=on_token,
        )

    # -- per-step decisions --------------------------------------------

    def admit(self) -> list[Admission]:
        """Lease slots to waiting requests (FIFO), lowest free slot
        first, evicting LRU prefix-cache donors under slot pressure —
        all deterministic for the SPMD contract. Returns the wave's
        :class:`Admission` plan (donor + reuse length resolved per
        request); the engine executes the copies and prefills.

        Donor pinning: a donor chosen for one admission is pinned so a
        LATER admission in the same wave cannot evict (and overwrite)
        it before the engine's copy program has read it. When the only
        evictable slot IS the pinned donor, reuse is dropped for that
        request (admitted cold into the evicted donor) — admission
        progress beats prefix reuse, and stalling here would livelock a
        one-slot engine whose sole donor matches the queue head."""
        admitted: list[Admission] = []
        pinned: list[int] = []
        cache = self.prefix_cache
        while self.waiting:
            req = self.waiting[0]
            donor, reuse = (None, 0)
            if cache is not None:
                # match() is PURE; hit/LRU accounting commits only if
                # the admission lands (a blocked queue head is probed
                # every step and must not skew stats or eviction order)
                donor, reuse = cache.match(req.prompt)
                if donor is not None and reuse < self.prefix_min_reuse:
                    donor, reuse = None, 0  # too shallow to pay a copy
                if donor is not None:
                    cache.pin(donor)
                    pinned.append(donor)
            if self._free:
                slot = self._free.pop(0)
            else:
                slot = cache.evict_lru() if cache is not None else None
                if slot is None and donor is not None:
                    # the pinned donor may be the only evictable slot:
                    # fall back to a cold admission
                    cache.unpin(donor)
                    pinned.pop()
                    donor, reuse = None, 0
                    slot = cache.evict_lru()
                if slot is None:
                    break  # genuinely full — request keeps waiting
            self.waiting.popleft()
            if cache is not None:
                cache.remove(slot)  # rows are about to be overwritten
                if donor is not None:
                    cache.commit_hit(donor, reuse)
                else:
                    cache.record_miss()
            req.slot = slot
            req.reused_tokens = reuse
            self.active[slot] = req
            (self._m_admit_hit if donor is not None
             else self._m_admit_cold).inc()
            admitted.append(
                Admission(req=req, slot=slot, donor_slot=donor,
                          reuse_len=reuse)
            )
        # the engine copies donor rows synchronously right after this
        # wave returns and nothing can evict before the next admit()
        # call, so wave-scoped pins release here
        if cache is not None:
            for slot in pinned:
                cache.unpin(slot)
        self._m_waiting.set(len(self.waiting))
        return admitted

    def on_prefill_complete(self, req: Request) -> None:
        """Register the request's prompt rows as a reusable prefix (its
        slot's first ``len(prompt)`` rows now hold that K/V)."""
        if self.prefix_cache is not None and req.slot is not None:
            self.prefix_cache.insert(req.slot, req.prompt)

    def flush_prefix_cache(self) -> None:
        """Invalidate every cached prefix and return donor slots to the
        free list (weight refresh: resident rows are stale)."""
        if self.prefix_cache is None:
            return
        self._free.extend(self.prefix_cache.flush())
        self._free.sort()

    def on_token(self, slot: int, token: int) -> bool:
        """Record one generated token for the slot's occupant; returns
        True when the request just finished (EOS or budget) — the
        caller then reclaims the slot."""
        req = self.active[slot]
        req.tokens.append(int(token))
        if (
            req.eos_id is not None and int(token) == req.eos_id
        ) or len(req.tokens) >= req.max_new_tokens:
            req.done = True
            return True
        return False

    def reclaim(self, slot: int) -> Request:
        """Free the slot immediately — the next :meth:`admit` can hand
        it to a waiting request in the same engine step. With the
        prefix cache on, a slot whose prompt rows are indexed is
        RETAINED as a donor instead (evicted LRU under pressure)."""
        req = self.active.pop(slot)
        req.slot = None
        if self.prefix_cache is not None and self.prefix_cache.release(slot):
            return req  # resident donor — off the free list
        self._free.append(slot)
        self._free.sort()
        return req

    def note_step(self) -> None:
        self._steps += 1
        self._busy_slot_steps += len(self.active)

    # -- introspection -------------------------------------------------

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    @property
    def occupancy(self) -> float:
        """Mean busy-slot fraction over all decode steps so far."""
        if self._steps == 0:
            return 0.0
        return self._busy_slot_steps / (self._steps * self.num_slots)

    def bucket_for(self, prompt_len: int) -> int:
        return bucket_for(prompt_len, self.buckets)
