"""Iteration-level request scheduling (Orca-style continuous batching).

Pure host-side bookkeeping — no jax anywhere: the scheduler decides
*which* request occupies *which* slot at each engine step, and the
engine turns those decisions into fixed-shape device programs. Keeping
this layer free of device state is what makes it trivially SPMD-safe:
every gang process runs the identical deterministic schedule from the
identical submission order (the same contract ``generate()`` already
imposes).

Admission is greedy FIFO into free slots at every step boundary
(requests submitted mid-flight join the next step's admission wave —
no generation "epoch" to wait for), and slots reclaim the moment a
sequence hits EOS or its token budget, so the freed compute is re-used
by the very next waiting request instead of idling until the batch
drains.

Prompt lengths are padded up to a fixed **bucket ladder**
(:func:`default_buckets`: powers of two, capped at the model's
``maxlen``) so the engine compiles one prefill program per bucket and
ONE decode program total — a small closed shape set, killing the
recompile churn the one-shot path's 16-entry jit cache only papers
over.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field


def default_buckets(max_len: int, floor: int = 16) -> tuple[int, ...]:
    """Power-of-two prompt buckets ``[floor, 2·floor, ..]`` capped at
    (and always including) ``max_len``."""
    if max_len <= 0:
        raise ValueError(f"max_len must be positive, got {max_len}")
    buckets = []
    b = max(1, floor)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


def bucket_for(prompt_len: int, buckets) -> int:
    """Smallest bucket holding ``prompt_len`` tokens."""
    for b in buckets:
        if b >= prompt_len:
            return int(b)
    raise ValueError(
        f"prompt of {prompt_len} tokens exceeds the largest bucket "
        f"{max(buckets)}"
    )


@dataclass
class Request:
    """One in-flight generation request.

    ``tokens`` accumulates the GENERATED continuation only (the prompt
    is not repeated there); ``emitted`` marks how many of those the
    caller has already consumed via the streaming iterator.
    ``on_token(token, done)`` is an optional per-token consumer
    callback; when it raises, the engine fails THIS request (``error``
    set, slot reclaimed) and keeps serving the rest."""

    rid: int
    prompt: tuple
    max_new_tokens: int
    temperature: float = 0.0
    eos_id: int | None = None
    tokens: list = field(default_factory=list)
    slot: int | None = None
    done: bool = False
    emitted: int = 0
    submit_time: float | None = None
    finish_time: float | None = None
    on_token: object | None = None
    error: BaseException | None = None

    @property
    def full_sequence(self) -> list:
        return list(self.prompt) + self.tokens


class Scheduler:
    """FIFO queue + slot lease tracking for :class:`InferenceEngine`."""

    def __init__(self, num_slots: int, buckets):
        self.num_slots = int(num_slots)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.waiting: deque[Request] = deque()
        self.active: dict[int, Request] = {}
        self._free: list[int] = list(range(self.num_slots))
        self._ids = itertools.count()
        # occupancy accounting for the serving bench
        self._steps = 0
        self._busy_slot_steps = 0

    # -- submission ----------------------------------------------------

    def submit(self, request: Request) -> Request:
        request.rid = next(self._ids) if request.rid is None else request.rid
        self.waiting.append(request)
        return request

    def make_request(self, prompt, max_new_tokens, temperature=0.0,
                     eos_id=None, on_token=None) -> Request:
        return Request(
            rid=next(self._ids),
            prompt=tuple(int(t) for t in prompt),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            eos_id=None if eos_id is None else int(eos_id),
            on_token=on_token,
        )

    # -- per-step decisions --------------------------------------------

    def admit(self) -> list[Request]:
        """Lease free slots to waiting requests (FIFO), lowest slot
        first — deterministic for the SPMD contract. Returns the newly
        admitted requests (their ``slot`` set); the engine prefills
        each."""
        admitted = []
        while self.waiting and self._free:
            req = self.waiting.popleft()
            req.slot = self._free.pop(0)
            self.active[req.slot] = req
            admitted.append(req)
        return admitted

    def on_token(self, slot: int, token: int) -> bool:
        """Record one generated token for the slot's occupant; returns
        True when the request just finished (EOS or budget) — the
        caller then reclaims the slot."""
        req = self.active[slot]
        req.tokens.append(int(token))
        if (
            req.eos_id is not None and int(token) == req.eos_id
        ) or len(req.tokens) >= req.max_new_tokens:
            req.done = True
            return True
        return False

    def reclaim(self, slot: int) -> Request:
        """Free the slot immediately — the next :meth:`admit` can hand
        it to a waiting request in the same engine step."""
        req = self.active.pop(slot)
        req.slot = None
        self._free.append(slot)
        self._free.sort()
        return req

    def note_step(self) -> None:
        self._steps += 1
        self._busy_slot_steps += len(self.active)

    # -- introspection -------------------------------------------------

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    @property
    def occupancy(self) -> float:
        """Mean busy-slot fraction over all decode steps so far."""
        if self._steps == 0:
            return 0.0
        return self._busy_slot_steps / (self._steps * self.num_slots)

    def bucket_for(self, prompt_len: int) -> int:
        return bucket_for(prompt_len, self.buckets)
