"""Paged KV arena: block-pool K/V + block-table attention (ISSUE 7).

The fixed slot arena (:mod:`~elephas_tpu.serving.kv_cache`) prices
every slot at the model's worst-case length — one ``[num_slots,
max_len, H, Dh]`` row pair per layer, so a single long-context slot's
reservation caps admission depth for everyone. This module is the
PagedAttention-style (vLLM, Kwon et al. 2023) replacement: a global
**block pool** ``[num_blocks, block_size, H, Dh]`` per layer plus
per-slot **block tables** mapping logical position ``p`` to physical
row ``(table[p // block_size], p % block_size)``. Requests reserve
``ceil((prompt + max_new_tokens) / block_size)`` blocks — their OWN
worst case, not the model's — so short requests stop paying for long
ones, freed blocks recycle at block granularity, and full prompt-prefix
blocks can be SHARED by refcount (copy-free prefix hits, no donor
transplant program at all).

The repo's serving invariants carry over unchanged:

- **one-hot slot-local writes** — block/offset targets are one-hot
  contractions, never dynamic scatters, so writes stay exact (each
  pool row receives exactly one ``1.0·value`` against ``0.0`` terms)
  and mesh-safe;
- **a closed compiled-shape set** — programs compile per bucketed
  block-TABLE length (:func:`table_buckets`: powers of two in blocks,
  capped at ``ceil(maxlen / block_size)``), not per request: the decode
  program's attention span is ``T·block_size`` for the bucketed ``T``
  covering the longest live table, so a short-context steady state
  attends over a short span instead of ``maxlen``;
- **temperature-0 token-exactness** — attention runs the same
  einsum/softmax math over the same visible position set as the fixed
  arena, including under TP meshes (heads shard over the model axis;
  the block axis stays REPLICATED — blocks have no slot affinity, so
  unlike the slot arena there is no batch-axis sharding that keeps a
  gather local; the one-hot contractions remain exact regardless).

Padding convention: block-table rows pad with the SENTINEL id
``num_blocks`` — a one-hot against ``arange(num_blocks)`` that matches
nothing, so padded entries neither write (a cursor beyond a slot's
table maps to no pool row) nor gather (they contribute exact zero rows,
masked off by position visibility). Padding with 0 would alias block 0.

:func:`gather_blocks` / :func:`scatter_blocks` are the device half of
preempt/resume: gather reads a victim's blocks into dense rows for
host offload (``jax.device_get``), scatter writes them back into a
fresh allocation bit-exactly. One compile per table bucket each.

The sentinel/table conventions here (pad with ``num_blocks``, route
cursor overrun to the sentinel, ``table_buckets`` ladder) are shared
verbatim by the pipeline-parallel engine's per-stage pools
(:mod:`elephas_tpu.serving.pp_engine`, ISSUE 15) — its stage-local
attention closures mirror this module's ``local=True`` fast path
inside ``shard_map``, where native gather/scatter is always legal.
"""

from __future__ import annotations

from elephas_tpu.models.transformer import (
    _apply_rope,
    _rope_tables,
)
from elephas_tpu.serving.kv_cache import (
    _graph_replay,
    _rows_at_position_matrix,
    _rows_at_positions,
    _slice_seq_at_position_matrix,
    _slice_seq_at_positions,
)

__all__ = [
    "PagedKVPool",
    "blocks_for",
    "table_buckets",
    "table_bucket_for",
    "paged_token_decode_step",
    "paged_chunk_forward",
    "paged_verify_forward",
    "gather_blocks",
    "scatter_blocks",
]


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions (ceil division)."""
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(block_size))


def table_buckets(max_blocks: int) -> tuple[int, ...]:
    """Power-of-two block-table length ladder ``[1, 2, 4, ..]`` capped
    at (and always including) ``max_blocks`` — the paged analogue of
    the prompt bucket ladder: programs compile once per bucket, so the
    compiled-shape set stays closed no matter the request mix."""
    if max_blocks <= 0:
        raise ValueError(
            f"max_blocks must be positive, got {max_blocks}"
        )
    buckets, b = [], 1
    while b < max_blocks:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_blocks))
    return tuple(buckets)


def table_bucket_for(n_blocks: int, buckets) -> int:
    """Smallest table bucket holding ``n_blocks`` blocks."""
    for b in buckets:
        if b >= n_blocks:
            return int(b)
    raise ValueError(
        f"block table of {n_blocks} blocks exceeds the largest table "
        f"bucket {max(buckets)}"
    )


class PagedKVPool:
    """Specs + sharding rules for the paged block pool of one model.

    The paged sibling of :class:`~elephas_tpu.serving.kv_cache.\
SlotKVCache`: host-side metadata only, the arrays are functional state
    threaded through the engine's jitted steps. Buffers are
    ``[num_blocks, block_size, H, Dh]`` per layer; heads shard over the
    model axis when they tile (same rule as the slot arena), but the
    BLOCK axis is replicated — a block belongs to whichever slot the
    allocator leased it to, so there is no batch-axis layout that keeps
    a table gather shard-local the way the slot arena's slot==batch
    alignment did. Under a DP mesh this costs pool replication per
    replica and a cross-replica reduction per write (exact: one-hot
    partial sums are zero everywhere but the owning row); TP meshes
    pay nothing new."""

    def __init__(self, flash_layers, num_blocks: int, block_size: int,
                 mesh=None, batch_axes=("data",), model_axis=None,
                 kv_dtype: str = "fp"):
        from elephas_tpu.serving.kv_quant import check_kv_dtype

        self.specs = [
            (l.name, int(l.num_heads), int(l.head_dim))
            for l in flash_layers
        ]
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.mesh = mesh
        self.batch_axes = tuple(
            (batch_axes,) if isinstance(batch_axes, str) else batch_axes
        )
        self.model_axis = model_axis
        self.kv_dtype = check_kv_dtype(kv_dtype)

    def nbytes(self) -> int:
        """Host-side size of the full block pool at its STORED dtype
        — f32 values for ``kv_dtype="fp"``, int8/int4-packed codes
        plus per-(position, head) f32 scales when quantized. This is
        the per-device KV price the equal-bytes bench gate divides
        by."""
        from elephas_tpu.serving.kv_quant import pool_bytes_per_pos

        return self.num_blocks * self.block_size * pool_bytes_per_pos(
            self.specs, self.kv_dtype
        )

    def constrain(self, z, heads: int):
        """``[num_blocks, block_size, H, Dh]`` buffers (and their
        3-D ``[num_blocks, block_size, H]`` scale planes): block axis
        replicated, heads over the model axis when they tile."""
        if self.mesh is None:
            return z
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        ax = (
            self.model_axis
            if self.model_axis is not None
            and self.mesh.shape.get(self.model_axis, 1) > 1
            and heads % self.mesh.shape[self.model_axis] == 0
            else None
        )
        spec = (
            P(None, None, ax, None) if z.ndim == 4 else P(None, None, ax)
        )
        return jax.lax.with_sharding_constraint(
            z, NamedSharding(self.mesh, spec)
        )

    def init(self) -> dict:
        """The zeroed pool: ``{layer_name: (k, v)}`` float32 for
        ``kv_dtype="fp"``; ``{layer_name: (kq, vq, k_scale, v_scale)}``
        when quantized — int8 ``[num_blocks, block_size, H, Dhp]``
        codes (``Dhp`` = packed head dim) beside f32 ``[num_blocks,
        block_size, H]`` scales. Zero codes with zero scales dequantize
        to exact zeros, so the sentinel-row convention is unchanged."""
        import jax.numpy as jnp

        from elephas_tpu.serving.kv_quant import packed_head_dim

        if self.kv_dtype == "fp":
            return {
                name: tuple(
                    self.constrain(
                        jnp.zeros(
                            (self.num_blocks, self.block_size, h, d),
                            jnp.float32,
                        ),
                        h,
                    )
                    for _ in range(2)
                )
                for name, h, d in self.specs
            }
        out = {}
        for name, h, d in self.specs:
            dp = packed_head_dim(d, self.kv_dtype)
            qz = lambda: self.constrain(
                jnp.zeros(
                    (self.num_blocks, self.block_size, h, dp), jnp.int8
                ),
                h,
            )
            sz = lambda: self.constrain(
                jnp.zeros(
                    (self.num_blocks, self.block_size, h), jnp.float32
                ),
                h,
            )
            out[name] = (qz(), qz(), sz(), sz())
        return out


def _exact_onehot_einsum(eq, sels, x, out_dtype):
    """One-hot contraction that stays EXACT for integer operands:
    int8 pool codes contract in int32 (each output element is a single
    nonzero term, so no overflow and no rounding) and cast back; float
    operands keep the existing f32 path bit-for-bit."""
    import jax.numpy as jnp

    if jnp.issubdtype(jnp.dtype(out_dtype), jnp.integer):
        ops = [s.astype(jnp.int32) for s in sels]
        ops.append(x.astype(jnp.int32))
        return jnp.einsum(eq, *ops).astype(out_dtype)
    ops = [s.astype(out_dtype) for s in sels]
    ops.append(x.astype(out_dtype))
    return jnp.einsum(eq, *ops)


def paged_token_decode_step(model, w, tok, positions, pool, tables,
                            block_size, maxlen, active, local=False,
                            attention="naive", kv_dtype="fp"):
    """One decode step over the whole slot population, paged: slot
    ``b`` consumes ``tok[b]`` at absolute position ``positions[b]``,
    writes that position's K/V into pool row ``(tables[b, p // bs],
    p % bs)``, and attends over its table's gathered blocks (positions
    ``<= positions[b]``).

    Same per-row math as the fixed arena's :func:`~elephas_tpu.serving.\
kv_cache.token_decode_step` — einsum strings and operation order kept
    identical so paged tokens match the fixed arena (and one-shot
    ``generate()``) exactly at temperature 0; only the storage indexing
    changes. ``tables`` is ``[num_slots, T]`` for a bucketed ``T``
    (compile per bucket); sentinel entries (``num_blocks``) match no
    pool row. ``active`` is REQUIRED here (unlike the fixed step):
    an inactive slot's stale cursor may map outside its table, and the
    sentinel only protects the table's padded tail, not a row another
    slot now owns.

    ``local=True`` (no mesh) swaps the one-hot contractions for native
    gather/scatter — bitwise the same rows land and load (a scatter
    writes the identical value the one-hot selected; garbage gathered
    through clipped sentinel ids only ever feeds visibility-masked
    lanes), but the gather work drops from O(B·T·num_blocks) to
    O(B·T) rows per step. Under a mesh the one-hots stay: dynamic
    gathers/scatters on sharded operands make GSPMD emit collectives
    inside the decode loop (the measured ~15x hazard the fixed arena
    also avoids).

    ``attention="flash"`` (ISSUE 11) runs the gathered table span
    through the tiled online-softmax kernel
    (:mod:`elephas_tpu.ops.flash_serving`) instead of materializing
    the ``[B, H, S]`` score row — float-tolerance parity, temp-0
    token-exact, same visible position set.

    ``kv_dtype`` ``"int8"``/``"int4"`` (ISSUE 19): the pool entry is a
    4-tuple ``(kq, vq, k_scale, v_scale)`` and this token's K/V rows
    QUANTIZE ON WRITE (:mod:`elephas_tpu.serving.kv_quant`) — codes
    and per-(position, head) scales land through the same one-hot /
    native-scatter machinery (integer contractions run in int32, so
    they stay exact), the table gather moves quantized bytes, and
    dequantization happens inside the flash tile loop (or over the
    full gathered span for the naive oracle). ``kv_dtype="fp"`` is
    bit-for-bit the historical program.

    Returns ``(logits [num_slots, vocab], new_pool)``."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.ops.flash_serving import flash_span_decode
    from elephas_tpu.serving.kv_quant import (
        dequantize_rows,
        quantize_rows,
    )

    bs = int(block_size)
    T = int(tables.shape[1])
    S = T * bs
    ctx_new = {}
    blk_idx = positions // bs
    off = positions % bs
    # the slot's CURRENT block id, via a one-hot over its table row
    # (tables is data — a dynamic gather would be per-row). Cursors
    # with blk_idx >= T (a finished slot still device-active for the
    # rest of a steps_per_sync window keeps advancing past its
    # reservation — and past the whole bucket when every live table is
    # small) match NO table column, and the where/sum would resolve to
    # 0 — a REAL block id, owned by whichever request leased block 0.
    # Route them to the sentinel explicitly; in-bucket overrun lands on
    # the table's sentinel padding by construction.
    t_onehot = blk_idx[:, None] == jnp.arange(T)[None, :]
    blk = jnp.sum(jnp.where(t_onehot, tables, 0), axis=1)  # [B]
    N_sentinel = next(iter(pool.values()))[0].shape[0]
    blk = jnp.where(blk_idx < T, blk, N_sentinel)

    quant = kv_dtype != "fp"

    def attn_for(op):
        def attn(x, *_a, **_k):
            entry = pool[op.name]
            if quant:
                pk, pv, sk, sv = entry
            else:
                pk, pv = entry
                sk = sv = None
            N = int(pk.shape[0])
            H, Dh = op.num_heads, op.head_dim
            Dhs = int(pk.shape[-1])  # stored width (packed for int4)
            B = x.shape[0]
            qkv = x @ w[op.qkv.kernel.path]  # [B, 3·H·Dh]
            q, k, v = jnp.split(
                qkv.reshape(B, 3, H, Dh), 3, axis=1
            )
            q, k, v = q[:, 0], k[:, 0], v[:, 0]  # [B, H, Dh]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos_t = _rows_at_positions(
                    jnp.asarray(cos_np), positions
                )[:, None, :]
                sin_t = _rows_at_positions(
                    jnp.asarray(sin_np), positions
                )[:, None, :]
                q = _apply_rope(q, cos_t, sin_t)
                k = _apply_rope(k, cos_t, sin_t)
            if quant:
                # quantize-on-write: the row's codes + scales are what
                # lands; fp k/v die with this trace
                k, ks = quantize_rows(k, kv_dtype)
                v, vs = quantize_rows(v, kv_dtype)
            gks = gvs = None
            if local:
                # unmeshed fast path: scatter this token's row at
                # (blk, off) — inactive/overrun cursors route to the
                # sentinel index and DROP — then gather the table's
                # rows natively (sentinel ids clip; they only feed
                # masked lanes)
                blk_safe = jnp.where(active, blk, N)
                pk = pk.at[blk_safe, off].set(
                    k.astype(pk.dtype), mode="drop"
                )
                pv = pv.at[blk_safe, off].set(
                    v.astype(pv.dtype), mode="drop"
                )
                gk = jnp.take(pk, tables, axis=0, mode="clip")
                gk = gk.reshape(B, S, H, Dhs)
                gv = jnp.take(pv, tables, axis=0, mode="clip")
                gv = gv.reshape(B, S, H, Dhs)
                if quant:
                    sk = sk.at[blk_safe, off].set(ks, mode="drop")
                    sv = sv.at[blk_safe, off].set(vs, mode="drop")
                    gks = jnp.take(
                        sk, tables, axis=0, mode="clip"
                    ).reshape(B, S, H)
                    gvs = jnp.take(
                        sv, tables, axis=0, mode="clip"
                    ).reshape(B, S, H)
            else:
                # write: one token per active slot lands at (blk, off)
                # — factored one-hot contraction over (block, offset);
                # the sentinel id N matches nothing, so a padded/
                # overrun cursor writes nowhere
                wsel = (blk[:, None] == jnp.arange(N)[None, :]) \
                    & active[:, None]  # [B, N]
                osel = off[:, None] == jnp.arange(bs)[None, :]  # [B,bs]
                new_k = _exact_onehot_einsum(
                    "bn,bo,bhd->nohd", (wsel, osel), k, pk.dtype
                )
                new_v = _exact_onehot_einsum(
                    "bn,bo,bhd->nohd", (wsel, osel), v, pv.dtype
                )
                covered = (
                    jnp.einsum(
                        "bn,bo->no",
                        wsel.astype(jnp.int32), osel.astype(jnp.int32),
                    ) > 0
                )[:, :, None, None]
                pk = jnp.where(covered, new_k, pk)
                pv = jnp.where(covered, new_v, pv)
                # gather each slot's blocks into its dense [S, H, Dh]
                # view (sentinel table entries contribute exact zero
                # rows, all masked off by visibility)
                gsel = (
                    tables[:, :, None] == jnp.arange(N)[None, None, :]
                )  # [B, T, N]
                gk = _exact_onehot_einsum(
                    "btn,nohd->btohd", (gsel,), pk, pk.dtype
                ).reshape(B, S, H, Dhs)
                gv = _exact_onehot_einsum(
                    "btn,nohd->btohd", (gsel,), pv, pv.dtype
                ).reshape(B, S, H, Dhs)
                if quant:
                    new_ks = jnp.einsum(
                        "bn,bo,bh->noh",
                        wsel.astype(sk.dtype), osel.astype(sk.dtype),
                        ks,
                    )
                    new_vs = jnp.einsum(
                        "bn,bo,bh->noh",
                        wsel.astype(sv.dtype), osel.astype(sv.dtype),
                        vs,
                    )
                    sk = jnp.where(covered[..., 0], new_ks, sk)
                    sv = jnp.where(covered[..., 0], new_vs, sv)
                    gks = jnp.einsum(
                        "btn,noh->btoh", gsel.astype(sk.dtype), sk
                    ).reshape(B, S, H)
                    gvs = jnp.einsum(
                        "btn,noh->btoh", gsel.astype(sv.dtype), sv
                    ).reshape(B, S, H)
            if attention == "flash":
                o = flash_span_decode(
                    q, gk, gv, positions, scale=Dh**-0.5,
                    kv_dtype=kv_dtype,
                    kv_scales=(gks, gvs) if quant else None,
                ).reshape(B, H * Dh)
            else:
                if quant:
                    # naive oracle: dequantize the gathered span once
                    # (it materializes [B, H, S] scores anyway)
                    gk = dequantize_rows(gk, gks, kv_dtype, Dh)
                    gv = dequantize_rows(gv, gvs, kv_dtype, Dh)
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum("bhd,bshd->bhs", q, gk) * (Dh**-0.5)
                visible = (
                    jnp.arange(S)[None, None, :]
                    <= positions[:, None, None]
                )
                att = jax.nn.softmax(
                    jnp.where(visible, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum("bhs,bshd->bhd", att, gv).reshape(
                    B, H * Dh
                )
            ctx_new[op.name] = (
                (pk, pv, sk, sv) if quant else (pk, pv)
            )
            return (
                o @ w[op.proj.kernel.path] + w[op.proj.bias.path]
            )

        return attn

    logits = _graph_replay(
        model, w, tok, attn_for,
        lambda a: _slice_seq_at_positions(a, positions, maxlen),
    )
    return logits, {
        name: ctx_new.get(name, pool[name]) for name in pool
    }


def paged_chunk_forward(model, w, tokens_chunk, pool, tables, offsets,
                        chunk_lens, active, block_size, maxlen,
                        local=False, attention="naive",
                        kv_dtype="fp"):
    """Prefill a bounded chunk of each active slot's prompt into its
    block-table rows — the ONLY prefill program paged mode needs: a
    cold prompt is one full-width chunk from offset 0 (or several under
    ``prefill_chunk``), a prefix hit starts at its shared-block
    boundary, so there is no separate whole-bucket prefill and no copy
    program at all.

    The paged analogue of :func:`~elephas_tpu.serving.kv_cache.\
chunked_prefill_forward`: this chunk's K/V rows land in the pool FIRST
    (one-hot over (block, offset) via the table), then queries attend
    over the gathered table span — shared prefix blocks, earlier
    chunks, and the chunk's own causal part. Compiled per (chunk width
    ``C``, table bucket ``T``) pair — both from closed ladders.
    ``local``/``attention``/``kv_dtype`` as in
    :func:`paged_token_decode_step` — quantized pools land this
    chunk's codes + scales through the same write machinery and
    dequantize inside the flash tiles (or over the gathered span for
    the naive oracle).

    Returns ``(logits [num_slots, C, vocab], new_pool)``."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.ops.flash_serving import flash_span_chunk
    from elephas_tpu.serving.kv_quant import (
        dequantize_rows,
        quantize_rows,
    )

    bs = int(block_size)
    C = int(tokens_chunk.shape[1])
    T = int(tables.shape[1])
    S = T * bs
    ctx_new = {}
    pos_mat = offsets[:, None] + jnp.arange(C)[None, :]  # [B, C]
    valid = (
        active[:, None] & (jnp.arange(C)[None, :] < chunk_lens[:, None])
    )  # [B, C]
    blk_idx_mat = pos_mat // bs
    off_mat = pos_mat % bs
    t_onehot = (
        blk_idx_mat[:, :, None] == jnp.arange(T)[None, None, :]
    )  # [B, C, T]
    blk_mat = jnp.sum(
        jnp.where(t_onehot, tables[:, None, :], 0), axis=2
    )  # [B, C]

    quant = kv_dtype != "fp"

    def attn_for(op):
        def attn(x, *_a, **_k):
            entry = pool[op.name]
            if quant:
                pk, pv, sk, sv = entry
            else:
                pk, pv = entry
                sk = sv = None
            N = int(pk.shape[0])
            H, Dh = op.num_heads, op.head_dim
            Dhs = int(pk.shape[-1])  # stored width (packed for int4)
            B = x.shape[0]
            qkv = jnp.reshape(
                x @ w[op.qkv.kernel.path], (B, C, 3, H, Dh)
            )
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3,B,H,C,Dh]
            q, k, v = qkv[0], qkv[1], qkv[2]
            if getattr(op, "rope", False):
                cos_np, sin_np = _rope_tables(maxlen, Dh)
                cos = _rows_at_position_matrix(
                    jnp.asarray(cos_np), pos_mat
                )[:, None]  # [B, 1, C, Dh]
                sin = _rows_at_position_matrix(
                    jnp.asarray(sin_np), pos_mat
                )[:, None]
                q = _apply_rope(q, cos, sin)
                k = _apply_rope(k, cos, sin)
            k_rows = jnp.transpose(k, (0, 2, 1, 3))  # [B, C, H, Dh]
            v_rows = jnp.transpose(v, (0, 2, 1, 3))
            if quant:
                # quantize-on-write: codes + per-(pos, head) scales
                # are what lands; fp rows die with this trace
                k_rows, ks_rows = quantize_rows(k_rows, kv_dtype)
                v_rows, vs_rows = quantize_rows(v_rows, kv_dtype)
            gks = gvs = None
            if local:
                # unmeshed fast path: scatter the chunk's rows at
                # (blk, off) — padded/inactive lanes route to the
                # sentinel index and DROP — then gather natively
                blk_safe = jnp.where(valid, blk_mat, N)
                pk = pk.at[blk_safe, off_mat].set(
                    k_rows.astype(pk.dtype), mode="drop"
                )
                pv = pv.at[blk_safe, off_mat].set(
                    v_rows.astype(pv.dtype), mode="drop"
                )
                gk = jnp.take(pk, tables, axis=0, mode="clip")
                gk = gk.reshape(B, S, H, Dhs)
                gv = jnp.take(pv, tables, axis=0, mode="clip")
                gv = gv.reshape(B, S, H, Dhs)
                if quant:
                    sk = sk.at[blk_safe, off_mat].set(
                        ks_rows, mode="drop"
                    )
                    sv = sv.at[blk_safe, off_mat].set(
                        vs_rows, mode="drop"
                    )
                    gks = jnp.take(
                        sk, tables, axis=0, mode="clip"
                    ).reshape(B, S, H)
                    gvs = jnp.take(
                        sv, tables, axis=0, mode="clip"
                    ).reshape(B, S, H)
            else:
                # land the chunk's rows first: factored one-hot over
                # (block, offset); `valid` rides the block select so a
                # padded chunk tail (blk_mat resolved to 0) writes
                # nowhere
                nsel = (
                    blk_mat[:, :, None] == jnp.arange(N)[None, None, :]
                ) & valid[:, :, None]  # [B, C, N]
                osel = (
                    off_mat[:, :, None]
                    == jnp.arange(bs)[None, None, :]
                )  # [B, C, bs]
                scat_k = _exact_onehot_einsum(
                    "bcn,bco,bchd->nohd", (nsel, osel), k_rows,
                    pk.dtype,
                )
                scat_v = _exact_onehot_einsum(
                    "bcn,bco,bchd->nohd", (nsel, osel), v_rows,
                    pv.dtype,
                )
                covered = (
                    jnp.einsum(
                        "bcn,bco->no",
                        nsel.astype(jnp.int32),
                        osel.astype(jnp.int32),
                    ) > 0
                )[:, :, None, None]
                pk = jnp.where(covered, scat_k, pk)
                pv = jnp.where(covered, scat_v, pv)
                gsel = (
                    tables[:, :, None] == jnp.arange(N)[None, None, :]
                )  # [B, T, N]
                gk = _exact_onehot_einsum(
                    "btn,nohd->btohd", (gsel,), pk, pk.dtype
                ).reshape(B, S, H, Dhs)
                gv = _exact_onehot_einsum(
                    "btn,nohd->btohd", (gsel,), pv, pv.dtype
                ).reshape(B, S, H, Dhs)
                if quant:
                    scat_ks = jnp.einsum(
                        "bcn,bco,bch->noh",
                        nsel.astype(sk.dtype), osel.astype(sk.dtype),
                        ks_rows,
                    )
                    scat_vs = jnp.einsum(
                        "bcn,bco,bch->noh",
                        nsel.astype(sv.dtype), osel.astype(sv.dtype),
                        vs_rows,
                    )
                    sk = jnp.where(covered[..., 0], scat_ks, sk)
                    sv = jnp.where(covered[..., 0], scat_vs, sv)
                    gks = jnp.einsum(
                        "btn,noh->btoh", gsel.astype(sk.dtype), sk
                    ).reshape(B, S, H)
                    gvs = jnp.einsum(
                        "btn,noh->btoh", gsel.astype(sv.dtype), sv
                    ).reshape(B, S, H)
            if attention == "flash":
                o = flash_span_chunk(
                    q, gk, gv, pos_mat, scale=Dh**-0.5,
                    kv_dtype=kv_dtype,
                    kv_scales=(gks, gvs) if quant else None,
                )
            else:
                if quant:
                    # naive oracle: dequantize the gathered span once
                    gk = dequantize_rows(gk, gks, kv_dtype, Dh)
                    gv = dequantize_rows(gv, gvs, kv_dtype, Dh)
                # flash-lint: allow — the selectable naive oracle
                att = jnp.einsum(
                    "bhcd,bshd->bhcs", q, gk
                ) * (Dh**-0.5)
                visible = (
                    jnp.arange(S)[None, None, None, :]
                    <= pos_mat[:, None, :, None]
                )
                att = jax.nn.softmax(
                    jnp.where(visible, att, -jnp.inf), axis=-1
                )
                # flash-lint: allow — naive oracle att@V
                o = jnp.einsum("bhcs,bshd->bhcd", att, gv)
            o = jnp.reshape(
                jnp.transpose(o, (0, 2, 1, 3)), (B, C, H * Dh)
            )
            ctx_new[op.name] = (
                (pk, pv, sk, sv) if quant else (pk, pv)
            )
            return (
                o @ w[op.proj.kernel.path] + w[op.proj.bias.path]
            )

        return attn

    logits = _graph_replay(
        model, w, tokens_chunk, attn_for,
        lambda a: _slice_seq_at_position_matrix(a, pos_mat, maxlen),
    )
    return logits, {
        name: ctx_new.get(name, pool[name]) for name in pool
    }


def paged_verify_forward(model, w, tokens_window, pool, tables,
                         offsets, n_fed, active, block_size, maxlen,
                         local=False, attention="naive",
                         kv_dtype="fp"):
    """Batched K-token speculative verify over the PAGED arena (ISSUE
    8) — the block-table analogue of :func:`~elephas_tpu.serving.\
kv_cache.verify_forward`: slot ``b`` feeds its last sampled token plus
    drafted guesses at positions ``offsets[b] ..``, K/V lands in the
    slot's table blocks, and a logits row comes back per window
    position for the engine's accept-longest-matching-prefix rule.

    Delegates to :func:`paged_chunk_forward` (generated tokens instead
    of prompt tokens; same writes-land-first causal attention), so
    there is exactly one verify program per (window width ``K``,
    table bucket) pair — both from closed ladders. Rollback is free:
    a rejected tail's garbage rows live INSIDE blocks the request
    already reserved up front (``ceil((prompt + max_new) / bs)``),
    so rolling the cursor back never touches the allocator, and the
    rows are rewritten before any query can see them."""
    return paged_chunk_forward(
        model, w, tokens_window, pool, tables, offsets, n_fed, active,
        block_size, maxlen, local=local, attention=attention,
        kv_dtype=kv_dtype,
    )


def gather_blocks(pool, ids):
    """Read pool blocks ``ids`` (``[T]`` int32, sentinel-padded) into
    dense per-layer rows of shape ``[T, block_size, ...]`` — the
    device half of preemption offload: the caller ``device_get``s the
    result and frees the blocks. One-hot over the block axis (exact,
    mesh-safe — integer pool leaves contract in int32); sentinel rows
    read zeros and are sliced off on the host. LEAF-GENERIC over the
    pool's tuple arity: fp entries stay ``(k, v)``, quantized entries
    move all four of ``(kq, vq, k_scale, v_scale)`` — offloaded
    blocks stay quantized, values and scales travel together. The
    pool is NOT consumed."""
    import jax.numpy as jnp

    out = {}
    for name, leaves in pool.items():
        N = int(leaves[0].shape[0])
        sel = ids[:, None] == jnp.arange(N)[None, :]  # [T, N]
        out[name] = tuple(
            _exact_onehot_einsum("tn,n...->t...", (sel,), z, z.dtype)
            for z in leaves
        )
    return out


def scatter_blocks(pool, ids, rows):
    """Write dense rows back into pool blocks ``ids`` — the resume
    half of preempt/offload: restored rows are bitwise the offloaded
    ones (quantized codes and scales included — bit-exact WITHIN a
    kv_dtype), so the resumed request's attention sees exactly the
    K/V it had. Sentinel ids write nowhere. Leaf-generic like
    :func:`gather_blocks`. Returns the new pool."""
    import jax.numpy as jnp

    out = {}
    for name, leaves in pool.items():
        rleaves = rows[name]
        N = int(leaves[0].shape[0])
        sel = ids[:, None] == jnp.arange(N)[None, :]  # [T, N]
        covered = jnp.any(sel, axis=0)  # [N]
        merged = []
        for z, r in zip(leaves, rleaves):
            new_z = _exact_onehot_einsum(
                "tn,t...->n...", (sel,), r.astype(z.dtype), z.dtype
            )
            cov = covered.reshape((N,) + (1,) * (z.ndim - 1))
            merged.append(jnp.where(cov, new_z, z))
        out[name] = tuple(merged)
    return out
