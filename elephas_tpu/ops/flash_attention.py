"""Flash attention — blockwise online-softmax attention for TPU.

Forward is a Pallas kernel (see /opt/skills/guides/pallas_guide.md):
q/k/v blocks stream HBM→VMEM, scores hit the MXU tile-by-tile, and the
softmax runs online (running max ``m``, normalizer ``l``, accumulator
``acc`` live in VMEM scratch across the KV grid axis) — attention never
materializes the ``[S, S]`` score matrix in HBM, so memory is O(S·D)
instead of O(S²).

Backward uses the standard flash recurrences (dV = Pᵀ dO, dS = P∘(dP − Δ),
…) evaluated blockwise under ``lax.scan`` — O(S·D) residuals (just
q/k/v/out/LSE), XLA-fused. The whole op carries a ``jax.custom_vjp`` so it
drops into any ``jax.grad`` training step.

On non-TPU backends the same kernel runs in Pallas interpreter mode
(tests), keeping one code path.

Reference parity note: the reference has no attention op of its own (its
models call Keras layers); this op backs the transformer model family and
the sequence-parallel path (ring_attention), which SURVEY.md §5 lists as
absent upstream — a TPU-native extension, not a port.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


# -- forward kernel ----------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    j = pl.program_id(2)
    last_j = pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [BQ, D]
    k = k_ref[0]  # [BK, D]
    s = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [BQ, BK]

    if causal:
        i = pl.program_id(1)
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(cols <= rows, s, NEG_INF)

    m_prev = m_ref[:]  # [BQ, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)  # [BQ, BK]
    # fully-masked-so-far rows: m_new is still NEG_INF and s - m_new == 0
    # would make p == 1, accumulating phantom mass (the row would output
    # mean(V) instead of zeros). Zero p so l stays 0 for those rows.
    p = jnp.where(m_new <= NEG_INF * 0.5, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)  # [BQ, 1]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(j == last_j)
    def _finalize():
        l = l_ref[:]
        # fully-masked rows kept l == 0 via the p guard above; they output
        # zeros with lse == NEG_INF (zero weight in ring-attention merges)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_ref[:] + jnp.log(safe_l))[:, 0]


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    """[BH, S, D] inputs → (out [BH, S, D], lse [BH, S])."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise ValueError(
            f"sequence lengths ({s_q}, {s_k}) must be multiples of the "
            f"block sizes ({block_q}, {block_k})"
        )
    grid = (bh, s_q // block_q, s_k // block_k)
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse rides as [BH, 1, S] so the trailing block dims (1, block_q)
            # meet Mosaic's (equal-dim, 128-divisible) tiling rule
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        # keras symbolic builds trace with a polymorphic batch dim
        # (_DimExpr); CostEstimate requires concrete ints
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * s_q * s_k * d,
            bytes_accessed=(2 * bh * s_q * d + 2 * bh * s_k * d) * q.dtype.itemsize,
            transcendentals=bh * s_q * s_k,
        )
        if all(type(t) is int for t in (bh, s_q, s_k, d))
        else None,
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, 0, :]


# -- blockwise backward (flash recurrences, XLA-fused) ------------------


def _causal_mask(i, j, block_q, block_k):
    rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return cols <= rows


def _flash_backward(scale, causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq, nk = s_q // block_q, s_k // block_k
    f32 = jnp.float32

    qb = q.reshape(bh, nq, block_q, d).astype(f32)
    kb = k.reshape(bh, nk, block_k, d).astype(f32)
    vb = v.reshape(bh, nk, block_k, d).astype(f32)
    gb = g.reshape(bh, nq, block_q, d).astype(f32)
    lseb = lse.reshape(bh, nq, block_q)
    # Δ_i = rowsum(dO ∘ O)
    delta = jnp.sum(g.astype(f32) * out.astype(f32), axis=-1).reshape(
        bh, nq, block_q
    )

    def p_block(i, j, qi, kj, li):
        s = jnp.einsum("bqd,bkd->bqk", qi, kj, preferred_element_type=f32) * scale
        if causal:
            s = jnp.where(_causal_mask(i, j, block_q, block_k)[None], s, NEG_INF)
        p = jnp.exp(s - li[..., None])  # [bh, BQ, BK]
        # fully-masked rows carry lse == NEG_INF; exp(s - lse) would be 1
        return jnp.where(li[..., None] <= NEG_INF * 0.5, 0.0, p)

    # dq: for each query block, scan KV blocks
    def dq_for_block(i, qi, gi, li, di):
        def body(acc, j):
            kj, vj = kb[:, j], vb[:, j]
            p = p_block(i, j, qi, kj, li)
            dp = jnp.einsum("bqd,bkd->bqk", gi, vj, preferred_element_type=f32)
            ds = p * (dp - di[..., None])
            return acc + jnp.einsum(
                "bqk,bkd->bqd", ds, kj, preferred_element_type=f32
            ) * scale, None

        acc0 = jnp.zeros((bh, block_q, d), f32)
        acc, _ = jax.lax.scan(body, acc0, jnp.arange(nk))
        return acc

    dq = jax.vmap(dq_for_block, in_axes=(0, 1, 1, 1, 1), out_axes=1)(
        jnp.arange(nq), qb, gb, lseb, delta
    ).reshape(bh, s_q, d)

    # dk/dv: for each KV block, scan query blocks
    def dkv_for_block(j, kj, vj):
        def body(carry, i):
            dk_acc, dv_acc = carry
            qi, gi, li, di = qb[:, i], gb[:, i], lseb[:, i], delta[:, i]
            p = p_block(i, j, qi, kj, li)
            dv_acc = dv_acc + jnp.einsum(
                "bqk,bqd->bkd", p, gi, preferred_element_type=f32
            )
            dp = jnp.einsum("bqd,bkd->bqk", gi, vj, preferred_element_type=f32)
            ds = p * (dp - di[..., None])
            dk_acc = dk_acc + jnp.einsum(
                "bqk,bqd->bkd", ds, qi, preferred_element_type=f32
            ) * scale
            return (dk_acc, dv_acc), None

        z = jnp.zeros((bh, block_k, d), f32)
        (dk_acc, dv_acc), _ = jax.lax.scan(body, (z, z), jnp.arange(nq))
        return dk_acc, dv_acc

    dk, dv = jax.vmap(dkv_for_block, in_axes=(0, 1, 1), out_axes=1)(
        jnp.arange(nk), kb, vb
    )
    dk = dk.reshape(bh, s_k, d)
    dv = dv.reshape(bh, s_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# -- public op ---------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_rule(scale, causal, block_q, block_k, interpret, residuals, g):
    return _flash_backward(scale, causal, block_q, block_k, residuals, g)


_flash_attention_bhsd.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Blockwise attention. ``q/k/v``: ``[batch, heads, seq, head_dim]``
    (or ``[bh, seq, head_dim]``). Differentiable; O(seq) memory.

    ``block_q``/``block_k`` default to the module-level
    ``DEFAULT_BLOCK_Q``/``DEFAULT_BLOCK_K`` (resolved at CALL time, so
    benchmarks can sweep tile sizes globally without threading
    arguments through the model builders)."""
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    squeeze = q.ndim == 3
    if squeeze:
        q, k, v = q[None], k[None], v[None]
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    merged = lambda t, s: t.reshape(b * h, s, d)  # noqa: E731
    out = _flash_attention_bhsd(
        merged(q, s_q),
        merged(k, s_k),
        merged(v, s_k),
        float(scale),
        bool(causal),
        int(block_q),
        int(block_k),
        bool(interpret),
    )
    out = out.reshape(b, h, s_q, d)
    return out[0] if squeeze else out


def attention_reference(q, k, v, causal: bool = False, scale: float | None = None):
    """Naive O(S²)-memory attention — the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(q.dtype)
