"""Flash attention — blockwise online-softmax attention for TPU.

Forward is a Pallas kernel (see /opt/skills/guides/pallas_guide.md):
q/k/v blocks stream HBM→VMEM, scores hit the MXU tile-by-tile, and the
softmax runs online (running max ``m``, normalizer ``l``, accumulator
``acc`` live in VMEM scratch across the KV grid axis) — attention never
materializes the ``[S, S]`` score matrix in HBM, so memory is O(S·D)
instead of O(S²).

Backward uses the standard flash recurrences (dV = Pᵀ dO, dS = P∘(dP − Δ),
…) evaluated blockwise under ``lax.scan`` — O(S·D) residuals (just
q/k/v/out/LSE), XLA-fused. The whole op carries a ``jax.custom_vjp`` so it
drops into any ``jax.grad`` training step.

On non-TPU backends the same kernel runs in Pallas interpreter mode
(tests), keeping one code path.

Reference parity note: the reference has no attention op of its own (its
models call Keras layers); this op backs the transformer model family and
the sequence-parallel path (ring_attention), which SURVEY.md §5 lists as
absent upstream — a TPU-native extension, not a port.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


# -- forward kernel ----------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    # refs arrive squeezed to [BQ, D] / [BK, D] / [BQ, D] / [1, BQ]
    # (BlockSpec ``None`` dims), so one kernel serves both the separate
    # [BH, S, D] layout and the packed [B, S, 3, H, D] qkv layout
    j = pl.program_id(2)
    last_j = pl.num_programs(2) - 1

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:]  # [BQ, D]
    k = k_ref[:]  # [BK, D]
    s = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [BQ, BK]

    if causal:
        i = pl.program_id(1)
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(cols <= rows, s, NEG_INF)

    m_prev = m_ref[:]  # [BQ, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)  # [BQ, BK]
    # fully-masked-so-far rows: m_new is still NEG_INF and s - m_new == 0
    # would make p == 1, accumulating phantom mass (the row would output
    # mean(V) instead of zeros). Zero p so l stays 0 for those rows.
    p = jnp.where(m_new <= NEG_INF * 0.5, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)  # [BQ, 1]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_ref[:].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(j == last_j)
    def _finalize():
        l = l_ref[:]
        # fully-masked rows kept l == 0 via the p guard above; they output
        # zeros with lse == NEG_INF (zero weight in ring-attention merges)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, :] = (m_ref[:] + jnp.log(safe_l))[:, 0]


def _resolve_blocks(block_q, block_k, s_q, s_k):
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise ValueError(
            f"sequence lengths ({s_q}, {s_k}) must be multiples of the "
            f"block sizes ({block_q}, {block_k})"
        )
    return block_q, block_k


def _cost(bh, s_q, s_k, d, itemsize):
    # keras symbolic builds trace with a polymorphic batch dim
    # (_DimExpr); CostEstimate requires concrete ints
    if not all(type(t) is int for t in (bh, s_q, s_k, d)):
        return None
    return pl.CostEstimate(
        flops=4 * bh * s_q * s_k * d,
        bytes_accessed=(2 * bh * s_q * d + 2 * bh * s_k * d) * itemsize,
        transcendentals=bh * s_q * s_k,
    )


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    """[BH, S, D] inputs → (out [BH, S, D], lse [BH, S])."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q, block_k = _resolve_blocks(block_q, block_k, s_q, s_k)
    grid = (bh, s_q // block_q, s_k // block_k)
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse rides as [BH, 1, S] so the trailing block dims (1, block_q)
            # meet Mosaic's (equal-dim, 128-divisible) tiling rule
            pl.BlockSpec((None, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        cost_estimate=_cost(bh, s_q, s_k, d, q.dtype.itemsize),
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, 0, :]


def _fwd_kernel_grouped(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                        m_ref, l_ref, *, scale: float, causal: bool,
                        block_q: int, block_k: int, hp: int, d: int):
    """Online-softmax forward over a GROUP of ``hp`` lane-packed heads.

    Blocks arrive ``[BQ, hp·d]`` — ``hp`` heads side by side filling a
    128-lane tile (r5, VERDICT r4 #3c: head_dim-64 models previously
    fell back to the transposed layout and paid its copy kernels).
    Heads stay separate WITHOUT lane reshapes (Mosaic rejects the
    vector shape cast): each head's score dot runs over all ``hp·d``
    lanes with the OTHER heads' k lanes zeroed — mathematically the
    head's own ``d``-deep contraction, and the same MXU occupancy the
    transposed fallback gets from a ``d``-deep dot. Per-head softmax
    state lives in ``[hp, BQ, 1]`` scratch; the accumulator stays in
    the packed ``[BQ, hp·d]`` layout with per-head rescaling applied
    through lane masks."""
    j = pl.program_id(2)
    last_j = pl.num_programs(2) - 1
    w = hp * d

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:]  # [BQ, hp·d]
    k = k_ref[:]  # [BK, hp·d]
    v = v_ref[:]
    lanes_k = jax.lax.broadcasted_iota(jnp.int32, (block_k, w), 1)
    lanes_q = jax.lax.broadcasted_iota(jnp.int32, (block_q, w), 1)
    if causal:
        i = pl.program_id(1)
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        visible = cols <= rows

    for t in range(hp):
        sel_k = (lanes_k >= t * d) & (lanes_k < (t + 1) * d)
        sel_q = (lanes_q >= t * d) & (lanes_q < (t + 1) * d)
        k_t = jnp.where(sel_k, k, 0)
        # zeroed foreign lanes contribute nothing: this IS q_t · k_tᵀ
        s = jax.lax.dot_general(
            q, k_t,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [BQ, BK]
        if causal:
            s = jnp.where(visible, s, NEG_INF)
        m_prev = m_ref[t]  # [BQ, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # fully-masked-so-far rows would accumulate phantom mass (see
        # _fwd_kernel) — zero them so l stays 0
        p = jnp.where(m_new <= NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[t] = l_ref[t] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[t] = m_new
        v_t = jnp.where(sel_k, v, 0).astype(jnp.float32)
        contrib = jax.lax.dot_general(
            p, v_t,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, hp·d], nonzero only on head t's lanes
        acc_ref[:] = jnp.where(
            sel_q, acc_ref[:] * alpha + contrib, acc_ref[:]
        )

    @pl.when(j == last_j)
    def _finalize():
        l_packed = jnp.ones((block_q, w), jnp.float32)
        for t in range(hp):
            sel_q = (lanes_q >= t * d) & (lanes_q < (t + 1) * d)
            l_t = l_ref[t]
            l_packed = jnp.where(
                sel_q, jnp.where(l_t == 0.0, 1.0, l_t), l_packed
            )
            safe_l = jnp.where(l_t == 0.0, 1.0, l_t)
            lse_ref[t, :] = (m_ref[t] + jnp.log(safe_l))[:, 0]
        o_ref[:] = (acc_ref[:] / l_packed).astype(o_ref.dtype)


def _flash_forward_packed(qkv, h, d, scale, causal, block_q, block_k,
                          interpret):
    """Packed qkv → (out ``[B, S, H·D]``, lse ``[B·H, S]``).

    ``qkv``: ``[B, S, 3·H·D]`` — the fused projection's output, as
    produced. The kernel reads q/k/v via three index maps over the ONE
    flat array (head ``h`` of q/k/v lives at last-dim block index
    ``h`` / ``H+h`` / ``2H+h`` in D-sized blocks), so the
    [B,S,H,D]→[B,H,S,D] transposes — the top copy kernels in the r4
    trace — never materialize, and the output lands sequence-major
    ready for the out-projection. Mosaic's tiling rule needs the last
    BLOCK dim 128-divisible: ``D % 128 == 0`` uses per-head blocks;
    smaller head dims with ``128 % D == 0`` lane-pack ``128 // D``
    heads per block (r5) via :func:`_fwd_kernel_grouped`; callers gate
    on ``packed_layout_supported``."""
    if d % 128:
        # head_dim 64: two heads lane-packed per 128-wide block
        return _flash_forward_packed_grouped(
            qkv, h, d, scale, causal, block_q, block_k, interpret
        )
    b, s, fused = qkv.shape
    assert fused == 3 * h * d, (qkv.shape, h, d)
    block_q, block_k = _resolve_blocks(block_q, block_k, s, s)
    grid = (b * h, s // block_q, s // block_k)

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (None, block_q, d), lambda bh, i, j, h=h: (bh // h, i, bh % h)
            ),
            pl.BlockSpec(
                (None, block_k, d),
                lambda bh, i, j, h=h: (bh // h, j, h + bh % h),
            ),
            pl.BlockSpec(
                (None, block_k, d),
                lambda bh, i, j, h=h: (bh // h, j, 2 * h + bh % h),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, block_q, d), lambda bh, i, j, h=h: (bh // h, i, bh % h)
            ),
            pl.BlockSpec((None, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), qkv.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        cost_estimate=_cost(b * h, s, s, d, qkv.dtype.itemsize),
        interpret=interpret,
    )(qkv, qkv, qkv)
    return out, lse[:, 0, :]


def _flash_forward_packed_grouped(qkv, h, d, scale, causal, block_q,
                                  block_k, interpret):
    """Packed forward for small head dims: ``hp = 128 // d`` heads ride
    each 128-lane block (r5). Same index-map structure as the per-head
    path with groups in place of heads; heads within a group are
    contiguous in the fused layout, so the output flattens straight to
    ``[B, S, H·D]`` and lse to ``[B·H, S]``."""
    b, s, fused = qkv.shape
    assert fused == 3 * h * d, (qkv.shape, h, d)
    assert 128 % d == 0 and h % (128 // d) == 0, (
        "grouped layout needs 128 % head_dim == 0 and an even group "
        "split — gate callers on packed_layout_supported", h, d,
    )
    hp = 128 // d
    ng = h // hp  # lane groups per q/k/v region
    block_q, block_k = _resolve_blocks(block_q, block_k, s, s)
    grid = (b * ng, s // block_q, s // block_k)

    kernel = functools.partial(
        _fwd_kernel_grouped,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        hp=hp,
        d=d,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (None, block_q, hp * d),
                lambda bg, i, j, ng=ng: (bg // ng, i, bg % ng),
            ),
            pl.BlockSpec(
                (None, block_k, hp * d),
                lambda bg, i, j, ng=ng: (bg // ng, j, ng + bg % ng),
            ),
            pl.BlockSpec(
                (None, block_k, hp * d),
                lambda bg, i, j, ng=ng: (bg // ng, j, 2 * ng + bg % ng),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, block_q, hp * d),
                lambda bg, i, j, ng=ng: (bg // ng, i, bg % ng),
            ),
            pl.BlockSpec((None, hp, block_q), lambda bg, i, j: (bg, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), qkv.dtype),
            jax.ShapeDtypeStruct((b * ng, hp, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hp * d), jnp.float32),
            pltpu.VMEM((hp, block_q, 1), jnp.float32),
            pltpu.VMEM((hp, block_q, 1), jnp.float32),
        ],
        cost_estimate=_cost(b * h, s, s, d, qkv.dtype.itemsize),
        interpret=interpret,
    )(qkv, qkv, qkv)
    # [B·NG, hp, S] → [B·H, S]: group-major × within-group IS the head
    # order (heads of a group are lane-contiguous in the fused layout)
    return out, lse.reshape(b * h, s)


def packed_layout_supported(d: int, h: int) -> bool:
    """Can the packed-qkv kernels express this (head_dim, heads)?
    128-multiples use per-head blocks; head_dim 64 lane-packs 2 heads
    per block (even head counts). Smaller head dims would multiply the
    masked-dot MAC waste past the fallback's copy cost, so they take
    the transposed layout."""
    return d % 128 == 0 or (d == 64 and h % 2 == 0)


# -- blockwise backward (flash recurrences, XLA-fused) ------------------


def _causal_mask(i, j, block_q, block_k):
    rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return cols <= rows


def _flash_backward(scale, causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    nq, nk = s_q // block_q, s_k // block_k
    f32 = jnp.float32

    qb = q.reshape(bh, nq, block_q, d).astype(f32)
    kb = k.reshape(bh, nk, block_k, d).astype(f32)
    vb = v.reshape(bh, nk, block_k, d).astype(f32)
    gb = g.reshape(bh, nq, block_q, d).astype(f32)
    lseb = lse.reshape(bh, nq, block_q)
    # Δ_i = rowsum(dO ∘ O)
    delta = jnp.sum(g.astype(f32) * out.astype(f32), axis=-1).reshape(
        bh, nq, block_q
    )

    def p_block(i, j, qi, kj, li):
        s = jnp.einsum("bqd,bkd->bqk", qi, kj, preferred_element_type=f32) * scale
        if causal:
            s = jnp.where(_causal_mask(i, j, block_q, block_k)[None], s, NEG_INF)
        p = jnp.exp(s - li[..., None])  # [bh, BQ, BK]
        # fully-masked rows carry lse == NEG_INF; exp(s - lse) would be 1
        return jnp.where(li[..., None] <= NEG_INF * 0.5, 0.0, p)

    # dq: for each query block, scan KV blocks
    def dq_for_block(i, qi, gi, li, di):
        def body(acc, j):
            kj, vj = kb[:, j], vb[:, j]
            p = p_block(i, j, qi, kj, li)
            dp = jnp.einsum("bqd,bkd->bqk", gi, vj, preferred_element_type=f32)
            ds = p * (dp - di[..., None])
            return acc + jnp.einsum(
                "bqk,bkd->bqd", ds, kj, preferred_element_type=f32
            ) * scale, None

        acc0 = jnp.zeros((bh, block_q, d), f32)
        acc, _ = jax.lax.scan(body, acc0, jnp.arange(nk))
        return acc

    dq = jax.vmap(dq_for_block, in_axes=(0, 1, 1, 1, 1), out_axes=1)(
        jnp.arange(nq), qb, gb, lseb, delta
    ).reshape(bh, s_q, d)

    # dk/dv: for each KV block, scan query blocks
    def dkv_for_block(j, kj, vj):
        def body(carry, i):
            dk_acc, dv_acc = carry
            qi, gi, li, di = qb[:, i], gb[:, i], lseb[:, i], delta[:, i]
            p = p_block(i, j, qi, kj, li)
            dv_acc = dv_acc + jnp.einsum(
                "bqk,bqd->bkd", p, gi, preferred_element_type=f32
            )
            dp = jnp.einsum("bqd,bkd->bqk", gi, vj, preferred_element_type=f32)
            ds = p * (dp - di[..., None])
            dk_acc = dk_acc + jnp.einsum(
                "bqk,bqd->bkd", ds, qi, preferred_element_type=f32
            ) * scale
            return (dk_acc, dv_acc), None

        z = jnp.zeros((bh, block_k, d), f32)
        (dk_acc, dv_acc), _ = jax.lax.scan(body, (z, z), jnp.arange(nq))
        return dk_acc, dv_acc

    dk, dv = jax.vmap(dkv_for_block, in_axes=(0, 1, 1), out_axes=1)(
        jnp.arange(nk), kb, vb
    )
    dk = dk.reshape(bh, s_k, d)
    dv = dv.reshape(bh, s_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_backward_packed(scale, causal, block_q, block_k, residuals, g):
    """Flash backward for the packed layout: the head-free
    :func:`_flash_backward` vmapped over the head axis of the
    ``[B, S, H, D]`` views — identical recurrences (one copy of the
    numerically delicate math), batched einsums, no bhsd transposes
    materialized. Returns ``(d(qkv) [B, S, 3, H, D],)``."""
    qkv, out, lse = residuals
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, S, H, D]

    def per_head(q_, k_, v_, o_, l_, g_):
        return _flash_backward(
            scale, causal, block_q, block_k, (q_, k_, v_, o_, l_), g_
        )

    dq, dk, dv = jax.vmap(
        per_head, in_axes=(2, 2, 2, 2, 1, 2), out_axes=2
    )(q, k, v, out, lse, g)
    return (jnp.stack([dq, dk, dv], axis=2),)


# -- public op ---------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_rule(scale, causal, block_q, block_k, interpret, residuals, g):
    return _flash_backward(scale, causal, block_q, block_k, residuals, g)


_flash_attention_bhsd.defvjp(_fwd_rule, _bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _flash_attention_packed(qkv, scale, causal, block_q, block_k, interpret):
    b, s, _, h, d = qkv.shape
    out, _ = _flash_forward_packed(
        qkv.reshape(b, s, 3 * h * d), h, d, scale, causal, block_q,
        block_k, interpret,
    )
    return out.reshape(b, s, h, d)


def _fwd_rule_packed(qkv, scale, causal, block_q, block_k, interpret):
    b, s, _, h, d = qkv.shape
    out, lse = _flash_forward_packed(
        qkv.reshape(b, s, 3 * h * d), h, d, scale, causal, block_q,
        block_k, interpret,
    )
    out = out.reshape(b, s, h, d)
    return out, (qkv, out, lse.reshape(b, h, s))


def _bwd_rule_packed(scale, causal, block_q, block_k, interpret, residuals, g):
    return _flash_backward_packed(
        scale, causal, block_q, block_k, residuals, g
    )


_flash_attention_packed.defvjp(_fwd_rule_packed, _bwd_rule_packed)


def flash_attention_qkv(
    qkv,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Self-attention straight from a fused qkv projection.

    ``qkv``: ``[B, S, 3, H, D]`` — the packed output of one
    ``Dense(3·H·D)`` reshaped, exactly as produced. Returns
    ``[B, S, H, D]``. Numerically identical to
    ``flash_attention(q, k, v)`` on the unpacked slices, but the kernel
    reads q/k/v via three index maps over the ONE packed array and
    writes output in the sequence-major layout the next projection
    consumes — the [B,S,·,H,D]→[·,B,H,S,D] transpose copies (the
    largest copy kernels in the r4 transformer trace, fwd and bwd)
    never exist. Differentiable (custom VJP in the same layout)."""
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K
    if scale is None:
        scale = qkv.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    b, s, _, h, d = qkv.shape
    if not packed_layout_supported(int(d), int(h)):
        # Mosaic's tiling rule needs 128-divisible last-dim blocks.
        # D % 128 == 0 → per-head blocks; divisors of 128 lane-pack
        # 128//D heads per block (r5 — head_dim-64 models no longer pay
        # the transpose copies); anything else (or an odd head count)
        # takes the transposed layout — same math, with the copy cost
        # the packed path avoids
        qkv_t = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3, B, H, S, D]
        out = _flash_attention_bhsd(
            qkv_t[0].reshape(b * h, s, d),
            qkv_t[1].reshape(b * h, s, d),
            qkv_t[2].reshape(b * h, s, d),
            float(scale),
            bool(causal),
            int(block_q),
            int(block_k),
            bool(interpret),
        )
        return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))
    return _flash_attention_packed(
        qkv,
        float(scale),
        bool(causal),
        int(block_q),
        int(block_k),
        bool(interpret),
    )


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Blockwise attention. ``q/k/v``: ``[batch, heads, seq, head_dim]``
    (or ``[bh, seq, head_dim]``). Differentiable; O(seq) memory.

    ``block_q``/``block_k`` default to the module-level
    ``DEFAULT_BLOCK_Q``/``DEFAULT_BLOCK_K`` (resolved at CALL time, so
    benchmarks can sweep tile sizes globally without threading
    arguments through the model builders)."""
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    squeeze = q.ndim == 3
    if squeeze:
        q, k, v = q[None], k[None], v[None]
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    merged = lambda t, s: t.reshape(b * h, s, d)  # noqa: E731
    out = _flash_attention_bhsd(
        merged(q, s_q),
        merged(k, s_k),
        merged(v, s_k),
        float(scale),
        bool(causal),
        int(block_q),
        int(block_k),
        bool(interpret),
    )
    out = out.reshape(b, h, s_q, d)
    return out[0] if squeeze else out


def attention_reference(q, k, v, causal: bool = False, scale: float | None = None):
    """Naive O(S²)-memory attention — the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(q.dtype)
