"""Ulysses sequence parallelism — all-to-all head↔sequence resharding.

The second of the two standard SP families (SURVEY.md §2a lists both as
absent upstream; TPU-native extension, not a port):

- **Ring** (:mod:`elephas_tpu.ops.ring_attention`): queries stay put,
  KV shards rotate via ``ppermute`` — S/W communication per hop, W hops.
- **Ulysses** (this module, after DeepSpeed-Ulysses): two
  ``lax.all_to_all`` reshards instead. Tokens arrive sequence-sharded
  ``[B, H, S/W, D]``; the first all-to-all trades the sequence split
  for a HEAD split (``[B, H/W, S, D]``), every device runs ordinary
  full-sequence attention over its own heads (here: the Pallas flash
  kernel), and the second all-to-all restores the sequence split.

Trade-offs, honestly: Ulysses moves each activation exactly twice
regardless of W (cheaper than the ring's rotating KV traffic for large
W), but requires ``num_heads % W == 0`` and materializes full-length
sequences per head group (O(S) per device rather than O(S/W)); the
ring has no head-count constraint and keeps O(S/W) activations. Both
are exact attention; pick by head count and memory budget.

Differentiable end-to-end with no custom VJP: ``all_to_all`` is linear
(its transpose is the reverse all-to-all) and the flash kernel carries
its own VJP.

Call :func:`ulysses_attention` INSIDE ``shard_map`` with the sequence
axis sharded over ``axis_name``; :func:`ulysses_attention_sharded` is
the global-array convenience wrapper (mirrors
``ring_attention_sharded``).
"""

from __future__ import annotations

import functools

import jax

from elephas_tpu.ops.flash_attention import flash_attention
from elephas_tpu.parallel.mesh import axis_size_compat, shard_map_compat


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Sequence-parallel attention; call INSIDE ``shard_map``.

    ``q/k/v``: the local sequence shard ``[B, H, S_local, D]`` (the
    sequence axis sharded over ``axis_name``; heads NOT sharded —
    ``H % axis_size == 0`` required). Returns ``[B, H, S_local, D]``.
    """
    w = axis_size_compat(axis_name)
    b, h, s_local, d = q.shape
    if h % w:
        raise ValueError(
            f"Ulysses needs num_heads ({h}) divisible by the sequence "
            f"axis size ({w}) — use ring attention for odd head counts"
        )

    import jax.numpy as jnp

    # ONE stacked all_to_all for q/k/v (as DeepSpeed-Ulysses does)
    # instead of three collective launches per attention:
    # [3, B, H, S/W, D] -> [3, B, H/W, S, D] — each device gets ALL the
    # sequence for a slice of the heads
    qh, kh, vh = jax.lax.all_to_all(
        jnp.stack((q, k, v)), axis_name, split_axis=2, concat_axis=3,
        tiled=True,
    )
    out = flash_attention(
        qh, kh, vh, causal=causal, scale=scale, interpret=interpret
    )
    # [B, H/W, S, D] -> [B, H, S/W, D]: restore the sequence split
    return jax.lax.all_to_all(
        out, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention_sharded(
    q,
    k,
    v,
    mesh,
    axis_name: str = "workers",
    causal: bool = False,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Global-array convenience wrapper: shards the sequence axis of
    ``[B, H, S, D]`` inputs over ``mesh[axis_name]`` and runs
    :func:`ulysses_attention` under ``shard_map``."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        ulysses_attention,
        axis_name=axis_name,
        causal=causal,
        scale=scale,
        interpret=interpret,
    )
    spec = P(None, None, axis_name, None)
    sharded = shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False,
    )
    return sharded(q, k, v)
