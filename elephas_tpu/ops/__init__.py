"""Hand-written TPU kernels and distributed ops.

The reference delegates all compute to TF kernels (SURVEY.md §2: "no
native code in the reference itself — TF kernels in C++/CUDA are the
delegated native layer"). Here the delegated layer is XLA, and this
package holds the ops where hand-scheduling beats the compiler:

- :mod:`elephas_tpu.ops.flash_attention` — blockwise online-softmax
  attention (Pallas, MXU-tiled, O(S) memory).
- :mod:`elephas_tpu.ops.ring_attention` — sequence-parallel attention
  over a mesh axis via ``ppermute`` (KV blocks rotate over ICI while
  each device computes its local query block).
"""

from elephas_tpu.ops.flash_attention import flash_attention
from elephas_tpu.ops.ring_attention import ring_attention
from elephas_tpu.ops.ulysses import ulysses_attention

__all__ = ["flash_attention", "ring_attention", "ulysses_attention"]
