"""Pipeline parallelism — GPipe-style SPMD pipeline over a mesh axis.

Absent from the reference (SURVEY.md §2a); provided as the TPU-native
construction used for stacks of identical blocks (the realistic PP case:
a transformer's repeated layers). Stage parameters are sharded over a
``('stages',)`` mesh axis — device ``s`` holds stage ``s``'s weights —
and microbatches flow through the ring: each tick every device applies
its stage to its current activation and hands the result to the next
device via ``lax.ppermute`` (one neighbor hop on ICI). With ``M``
microbatches and ``S`` stages the schedule runs ``M + S − 1`` ticks;
the ``(S−1)/M`` bubble fraction is the standard GPipe cost, amortized by
more microbatches.

The whole schedule is a ``lax.scan`` inside ``shard_map`` — one compiled
program, differentiable end-to-end (the backward pass pipelines in
reverse through the transposed ``ppermute``s automatically).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gpipe(stage_fn, stage_params, x_microbatches, axis_name: str):
    """Run microbatches through the stage pipeline; call INSIDE shard_map.

    ``stage_fn(params, x) -> y`` applies one stage (same signature and
    shapes for every stage; ``y.shape == x.shape``). ``stage_params`` is
    this device's stage's params (the caller shards a stacked-[S, ...]
    pytree over ``axis_name`` and passes the unstacked slice).
    ``x_microbatches``: ``[M, mb, ...]`` (replicated — only stage 0 reads
    it). Returns ``[M, mb, ...]`` outputs, replicated to all stages.
    """
    s = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + s - 1

    def one_tick(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, x_microbatches[mb_idx], recv)
        out = stage_fn(stage_params, inp)
        write_idx = t - (s - 1)
        is_valid = (stage == s - 1) & (write_idx >= 0)
        updated = outputs.at[jnp.clip(write_idx, 0, m - 1)].set(out)
        outputs = jnp.where(is_valid, updated, outputs)
        recv = jax.lax.ppermute(
            out, axis_name, [(i, (i + 1) % s) for i in range(s)]
        )
        return (recv, outputs), None

    recv0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros_like(x_microbatches)
    (recv, outputs), _ = jax.lax.scan(
        one_tick, (recv0, out0), jnp.arange(ticks)
    )
    # results live on the last stage; replicate them to every stage
    outputs = jnp.where(stage == s - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def gpipe_sharded(
    stage_fn,
    stacked_params,
    x,
    mesh,
    num_microbatches: int,
    axis_name: str = "stages",
):
    """Global-array wrapper: shards stacked ``[S, ...]`` stage params over
    ``mesh[axis_name]``, splits ``x [B, ...]`` into microbatches, runs
    :func:`gpipe`, and returns ``[B, ...]`` outputs."""
    from jax.sharding import PartitionSpec as P

    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} must divide into {num_microbatches} microbatches"
        )
    xm = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    def fn(params_slice, xm):
        params = jax.tree.map(lambda a: a[0], params_slice)
        return gpipe(stage_fn, params, xm, axis_name)

    sharded = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    out = sharded(stacked_params, xm)
    return out.reshape((b,) + out.shape[2:])
