"""Pipeline parallelism — GPipe-style SPMD pipeline over a mesh axis.

Absent from the reference (SURVEY.md §2a); provided as the TPU-native
construction. Stage parameters are sharded over a ``('stages',)`` mesh
axis — device ``s`` holds stage ``s``'s weights — and microbatches flow
through the ring: each tick every device applies its stage to its
current activation and hands the result to the next device via
``lax.ppermute`` (one neighbor hop on ICI). With ``M`` microbatches and
``S`` stages the schedule runs ``M + S − 1`` ticks; the ``(S−1)/M``
bubble fraction is the standard GPipe cost, amortized by more
microbatches.

Two surfaces:

- :func:`gpipe` / :func:`gpipe_sharded` — the homogeneous-stack
  primitive (identical stage shapes: a transformer's repeated blocks).
  One ``lax.scan`` inside ``shard_map``, differentiable end-to-end (the
  backward pass pipelines in reverse through the transposed
  ``ppermute``\\ s automatically). Outputs stay on the last stage and
  are sliced out per-stage-sharded — no whole-activation broadcast.
- :class:`GPipeTrainer` — a *training loop* over heterogeneous stages:
  per-stage activation shapes may all differ (activations ride a flat
  padded buffer; ``lax.switch`` picks the device's stage, so shapes
  stay static), the last stage computes the microbatch loss, gradients
  accumulate across microbatches inside one backward pipeline, and an
  optax optimizer updates the stage-sharded flat parameters in place —
  weights, grads, and optimizer slots all live ``P('stages')``-sharded;
  only neighbor activations cross the ICI ring.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elephas_tpu.parallel.mesh import (
    axis_size_compat,
    host_read,
    put_global,
    shard_map_compat,
)

logger = logging.getLogger(__name__)


def gpipe(stage_fn, stage_params, x_microbatches, axis_name: str):
    """Run microbatches through the stage pipeline; call INSIDE shard_map.

    ``stage_fn(params, x) -> y`` applies one stage (same signature and
    shapes for every stage; ``y.shape == x.shape`` — heterogeneous
    stages go through :class:`GPipeTrainer`). ``stage_params`` is this
    device's stage's params (the caller shards a stacked-[S, ...] pytree
    over ``axis_name`` and passes the unstacked slice).
    ``x_microbatches``: ``[M, mb, ...]`` (replicated — only stage 0
    reads it). Returns ``[M, mb, ...]`` outputs, VALID ON THE LAST STAGE
    ONLY (zeros elsewhere) — the caller slices the last stage's shard
    out instead of paying an all-reduce broadcast of whole activations.
    """
    s = axis_size_compat(axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + s - 1

    def one_tick(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, x_microbatches[mb_idx], recv)
        out = stage_fn(stage_params, inp)
        write_idx = t - (s - 1)
        is_valid = (stage == s - 1) & (write_idx >= 0)
        updated = outputs.at[jnp.clip(write_idx, 0, m - 1)].set(out)
        outputs = jnp.where(is_valid, updated, outputs)
        recv = jax.lax.ppermute(
            out, axis_name, [(i, (i + 1) % s) for i in range(s)]
        )
        return (recv, outputs), None

    recv0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros_like(x_microbatches)
    (recv, outputs), _ = jax.lax.scan(
        one_tick, (recv0, out0), jnp.arange(ticks)
    )
    return outputs


def gpipe_sharded(
    stage_fn,
    stacked_params,
    x,
    mesh,
    num_microbatches: int,
    axis_name: str = "stages",
):
    """Global-array wrapper: shards stacked ``[S, ...]`` stage params over
    ``mesh[axis_name]``, splits ``x [B, ...]`` into microbatches, runs
    :func:`gpipe`, and returns ``[B, ...]`` outputs (read from the last
    stage's shard — no cross-stage activation broadcast)."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} must divide into {num_microbatches} microbatches"
        )
    s = mesh.shape[axis_name]
    xm = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    def fn(params_slice, xm):
        params = jax.tree.map(lambda a: a[0], params_slice)
        out = gpipe(stage_fn, params, xm, axis_name)
        return out[None]  # leading per-stage axis

    sharded = shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(axis_name),
        check=False,
    )
    out = sharded(stacked_params, xm)[s - 1]
    return out.reshape((b,) + out.shape[2:])


def pipeline_mesh(
    num_stages: int,
    data_parallel: int = 1,
    axis_name: str = "stages",
    data_axis: str = "data",
    model_parallel: int = 1,
    model_axis: str = "model",
) -> Mesh:
    """Mesh for a (possibly data-replicated) pipeline: 1-D
    ``('stages',)`` when ``data_parallel == 1``, else a
    ``(data_parallel, num_stages)`` grid ``('data', 'stages')`` — each
    data row runs its own activation ring. With ``model_parallel > 1``
    (PP×TP, r5) a trailing model axis joins:
    ``('data', 'stages', 'model')`` — stage weights width-shard over it
    inside each ring position."""
    dp = int(data_parallel)
    mp = int(model_parallel)
    devices = jax.devices()
    if len(devices) < num_stages * dp * mp:
        raise ValueError(
            f"{num_stages} stages × {dp} data replicas × {mp} model "
            f"shards need {num_stages * dp * mp} devices, have "
            f"{len(devices)}"
        )
    if mp > 1:
        return Mesh(
            np.array(devices[: dp * num_stages * mp]).reshape(
                dp, num_stages, mp
            ),
            (data_axis, axis_name, model_axis),
        )
    if dp > 1:
        return Mesh(
            np.array(devices[: dp * num_stages]).reshape(dp, num_stages),
            (data_axis, axis_name),
        )
    return Mesh(np.array(devices[:num_stages]), (axis_name,))


class GPipeTrainer:
    """Microbatched pipeline-parallel trainer over heterogeneous stages.

    ``stage_fns``: list of ``fn(params, x) -> y`` — activation shapes may
    differ at every boundary. ``stage_params``: list of per-stage pytrees.
    ``loss_fn(y_pred, y) -> scalar`` (mean over the microbatch).

    Stateful stages (r4, VERDICT r3 weak #5 — BatchNorm through the
    pipe): pass ``stage_states`` (per-stage pytrees of non-trainable
    state) and stage functions of the extended signature
    ``fn(params, state, x, training) -> (y, new_state)``. The state
    rides a second stacked flat buffer ``[S, N_max]`` sharded over the
    stage axis alongside the parameters — each tick the owning stage
    reads and (on training ticks that carry REAL microbatch data, not
    pipeline-bubble garbage) writes its own slice; state never crosses
    the ring. BN statistics are therefore per-microbatch moving
    averages, the standard GPipe semantics. ``training=False`` builds
    the inference program (moving statistics, no state writes).

    TPU mapping: stage ``s``'s parameters are flattened
    (``ravel_pytree``), padded to the widest stage, and stacked
    ``[S, P_max]`` sharded over the ``('stages',)`` axis — so are the
    optimizer's moment slots. Activations cross stages as flat padded
    buffers through ``lax.ppermute``; ``lax.switch`` selects each
    device's stage so every reshape is static. One jitted train step
    runs the full forward pipeline, a reversed backward pipeline
    (gradient accumulation over microbatches for free via the scan
    transpose), and the optax update.
    """

    def __init__(
        self,
        stage_fns,
        stage_params,
        loss_fn,
        optimizer=None,
        mesh: Mesh | None = None,
        num_microbatches: int = 4,
        axis_name: str = "stages",
        data_parallel: int = 1,
        data_axis: str = "data",
        stage_states=None,
        model_axis: str | None = None,
    ):
        """PP×TP (r5, VERDICT r4 #4): pass ``model_axis`` (a THIRD
        mapped mesh axis) and per-stage-per-rank parameter pytrees —
        ``stage_params[s]`` becomes a LIST of ``mp`` pytrees (identical
        structure, rank-local weight shards). Stage functions then run
        Megatron-style on their rank's shards and may invoke collectives
        (``lax.psum``) over ``model_axis``; such collectives are legal
        inside the stage ``lax.switch`` because every device of a model
        group sits in the same stage and takes the same branch (an
        AUTO/GSPMD model axis instead deadlocks — its partitioner emits
        global-group collectives inside the diverging switch). Storage
        splits ``[S, mp, P_max]`` over ``P(stages, model)`` — weights,
        grads, and optimizer slots all hold 1/(S·mp) per device."""
        import optax
        from jax.flatten_util import ravel_pytree

        self.has_state = stage_states is not None
        if not self.has_state:
            # pure-stage API: fn(params, x) -> y; normalize to the
            # stateful signature with empty state
            stage_fns = [
                (lambda fn: lambda p, st, x, training: (fn(p, x), st))(f)
                for f in stage_fns
            ]
            stage_states = [{} for _ in stage_fns]
        self.stage_fns = list(stage_fns)
        self.loss_fn = loss_fn
        self.S = len(self.stage_fns)
        if self.S < 2:
            raise ValueError("a pipeline needs at least 2 stages")
        if len(stage_params) != self.S:
            raise ValueError(
                f"{len(stage_params)} param trees for {self.S} stages"
            )
        self.M = int(num_microbatches)
        self.axis = axis_name
        self.data_axis = data_axis
        if mesh is None:
            mesh = pipeline_mesh(
                self.S, int(data_parallel), axis_name=axis_name,
                data_axis=data_axis,
            )
        elif int(data_parallel) > 1 and mesh.shape.get(data_axis, 1) != int(
            data_parallel
        ):
            raise ValueError(
                f"data_parallel={data_parallel} conflicts with the "
                f"explicit mesh (its {data_axis!r} axis has size "
                f"{mesh.shape.get(data_axis, 1)}) — pass one or the other"
            )
        if mesh.shape[axis_name] != self.S:
            raise ValueError(
                f"mesh axis {axis_name!r} has size {mesh.shape[axis_name]}, "
                f"need {self.S} (one device per stage)"
            )
        self.dp = mesh.shape.get(data_axis, 1)
        self.mesh = mesh
        self.optimizer = optimizer or optax.adam(1e-2)
        self.model_axis = model_axis
        if model_axis is not None and model_axis not in mesh.shape:
            raise ValueError(
                f"model_axis {model_axis!r} not in mesh axes "
                f"{tuple(mesh.shape)}"
            )
        self.mp = mesh.shape.get(model_axis, 1) if model_axis else 1

        if self.mp > 1:
            # per-stage-per-rank pytrees: ravel each rank's shard (same
            # structure/shapes across ranks, so one unravel per stage)
            for s, ranks in enumerate(stage_params):
                if len(ranks) != self.mp:
                    raise ValueError(
                        f"stage {s} has {len(ranks)} rank shards for a "
                        f"{self.mp}-way model axis"
                    )
            rank_flats = [
                [ravel_pytree(r)[0] for r in ranks]
                for ranks in stage_params
            ]
            self._unravels = tuple(
                ravel_pytree(ranks[0])[1] for ranks in stage_params
            )
            self._p_sizes = [int(f[0].size) for f in rank_flats]
            self.P_max = max(self._p_sizes)
            stacked = np.stack(
                [
                    np.stack(
                        [
                            np.pad(
                                np.asarray(f, np.float32),
                                (0, self.P_max - f.size),
                            )
                            for f in franks
                        ]
                    )
                    for franks in rank_flats
                ]
            )  # [S, mp, P_max]
        else:
            flats, self._unravels = zip(
                *[ravel_pytree(p) for p in stage_params]
            )
            self._p_sizes = [int(f.size) for f in flats]
            self.P_max = max(self._p_sizes)
            stacked = np.stack(
                [
                    np.pad(
                        np.asarray(f, np.float32), (0, self.P_max - f.size)
                    )
                    for f in flats
                ]
            )
        sflats, self._state_unravels = zip(
            *[ravel_pytree(s) for s in stage_states]
        )
        self._s_sizes = [int(f.size) for f in sflats]
        self.N_max = max(1, max(self._s_sizes))  # never a 0-width buffer
        stacked_state = np.stack(
            [
                np.pad(
                    np.asarray(f, np.float32).reshape(-1),
                    (0, self.N_max - f.size),
                )
                for f in sflats
            ]
        )
        self._stage_sh = NamedSharding(mesh, P(axis_name))
        # params (and their optimizer slots) also split over the model
        # axis when one exists: [S, mp, P_max] over P(stages, model)
        self._param_sh = (
            NamedSharding(mesh, P(axis_name, model_axis))
            if self.mp > 1
            else self._stage_sh
        )
        self._rep_sh = NamedSharding(mesh, P())
        # microbatch spec: [M, mb, ...] rows split over the data axis
        self._mb_spec = P(None, data_axis) if self.dp > 1 else P()
        self._mb_sh = NamedSharding(mesh, self._mb_spec)
        self.params = put_global(stacked, self._param_sh)
        self.state = put_global(stacked_state, self._stage_sh)
        # optimizer slots mirror the stacked layout; scalar counters
        # replicate
        state_struct = jax.eval_shape(self.optimizer.init, self.params)
        state_sh = jax.tree.map(
            lambda s_: self._param_sh if s_.shape[:1] == (self.S,) else self._rep_sh,
            state_struct,
        )
        self.opt_state = jax.jit(
            self.optimizer.init, out_shardings=state_sh
        )(self.params)
        self._shapes = None  # boundary ShapeDtypeStructs, set at first fit
        self._train_steps = {}  # keyed by collect_outputs
        self._predict_fn = None

    # -- shape plumbing --------------------------------------------------

    def _infer_shapes(self, mb_example):
        """Chain eval_shape through the stages → S+1 boundary shapes."""
        shapes = [jax.eval_shape(lambda a: a, mb_example)]
        for s in range(self.S):
            params_struct = jax.eval_shape(
                self._unravels[s],
                jax.ShapeDtypeStruct((self._p_sizes[s],), jnp.float32),
            )
            state_struct = jax.eval_shape(
                self._state_unravels[s],
                jax.ShapeDtypeStruct((self._s_sizes[s],), jnp.float32),
            )
            fn = self.stage_fns[s]
            out_struct = jax.eval_shape(
                lambda p, st, x, _fn=fn: _fn(p, st, x, True)[0],
                params_struct, state_struct, shapes[-1],
            )
            shapes.append(out_struct)
        self._shapes = shapes
        self._elems = [int(np.prod(s.shape)) for s in shapes]
        # the ring only carries boundaries 1..S (stage 0 reads the typed
        # microbatch directly — int token ids never round-trip float32)
        self.B_max = max(self._elems[1:])
        self.mb_rows = int(shapes[0].shape[0])

    def _branches(self, training: bool):
        """Per-stage flat-buffer transforms with static shapes. Each
        branch gets ``(p, st, buf, xm_mb)``; stage 0 reads the typed
        microbatch ``xm_mb``, later stages the flat ring buffer. Returns
        ``(out_flat [B_max], new_state_flat [N_max])``."""
        from jax.flatten_util import ravel_pytree

        branches = []
        for s in range(self.S):
            in_shape = self._shapes[s].shape
            in_elems = self._elems[s]
            out_pad = self.B_max - self._elems[s + 1]
            fn = self.stage_fns[s]
            unravel = self._unravels[s]
            s_unravel = self._state_unravels[s]
            p_size = self._p_sizes[s]
            s_size = self._s_sizes[s]
            s_pad = self.N_max - s_size
            first = s == 0

            def branch(p, st, buf, xm_mb, fn=fn, unravel=unravel,
                       s_unravel=s_unravel, p_size=p_size, s_size=s_size,
                       s_pad=s_pad, in_shape=in_shape, in_elems=in_elems,
                       out_pad=out_pad, first=first):
                x = xm_mb if first else buf[:in_elems].reshape(in_shape)
                out, st_new = fn(
                    unravel(p[:p_size]), s_unravel(st[:s_size]), x,
                    training,
                )
                flat = out.reshape(-1).astype(jnp.float32)
                st_flat = ravel_pytree(st_new)[0].astype(jnp.float32)
                return (
                    jnp.pad(flat, (0, out_pad)),
                    jnp.pad(st_flat.reshape(-1), (0, s_pad)),
                )

            branches.append(branch)
        return branches

    # -- forward/loss ----------------------------------------------------

    def _forward(self, collect_outputs: bool, with_loss: bool = True,
                 training: bool = True):
        """Build the shard_map'd pipeline program.

        Returns ``fn(params, state, xm, ym) -> (loss, outputs, state')``
        with ``xm [M, mb, ...]`` microbatches (replicated, original
        dtype — only stage 0 reads them) and ``ym [M, ...]`` targets
        (replicated; only the last stage reads them, and only when
        ``with_loss``). ``loss`` comes back replicated (scalar psum);
        outputs, if collected, come back per-stage-sharded
        ``[S, M, out_elems]`` — the caller reads shard ``S-1``; the
        non-trainable state comes back stage-sharded ``[S, N_max]``,
        updated only on ticks where the stage processed REAL microbatch
        data (bubble ticks carry garbage and must not touch BN stats)
        and only when ``training``.
        """
        S, M, axis = self.S, self.M, self.axis
        branches = self._branches(training)
        out_elems = self._elems[-1]
        out_shape = self._shapes[-1].shape
        loss_fn = self.loss_fn

        def per_device(pflat, stflat, xm, ym):
            # [1, P] per device — or [1, 1, P] with a mapped model axis
            p = pflat.reshape(pflat.shape[-1])
            stage = jax.lax.axis_index(axis)
            is_last = stage == S - 1
            ticks = M + S - 1

            def one_tick(carry, t):
                recv, outputs, loss_sum, st = carry
                mb_idx = jnp.clip(t, 0, M - 1)
                out, st_new = jax.lax.switch(
                    stage,
                    [
                        lambda pp, ss, b, xmb, br=br: br(pp, ss, b, xmb)
                        for br in branches
                    ],
                    p,
                    st,
                    recv,
                    xm[mb_idx],
                )
                if training:
                    # stage s holds microbatch s <= t < s + M; outside
                    # that window the input is pipeline-bubble garbage
                    processing = (t >= stage) & (t < stage + M)
                    st = jnp.where(processing, st_new, st)
                write_idx = t - (S - 1)
                is_valid = is_last & (write_idx >= 0)
                widx = jnp.clip(write_idx, 0, M - 1)
                if with_loss:
                    # sanitize before the loss: non-last stages feed zeros
                    # so the untaken where-branch cannot generate NaNs
                    # that leak through the gradient of where()
                    y_pred = jnp.where(
                        is_valid, out[:out_elems], jnp.zeros((out_elems,))
                    ).reshape(out_shape)
                    mb_loss = loss_fn(y_pred, ym[widx])
                    # rank-1 accumulator on purpose: a RANK-0 scan-carry
                    # residual breaks jax<=0.4.3x shard_map's transpose
                    # (_SpecError on the scalar residual) when the
                    # pipeline is differentiated through
                    loss_sum = loss_sum + jnp.where(is_valid, mb_loss, 0.0)[None]
                if collect_outputs:
                    updated = outputs.at[widx].set(out[:out_elems])
                    outputs = jnp.where(is_valid, updated, outputs)
                recv = jax.lax.ppermute(
                    out, axis, [(i, (i + 1) % S) for i in range(S)]
                )
                return (recv, outputs, loss_sum, st), None

            recv0 = jnp.zeros((self.B_max,), jnp.float32)
            outputs0 = jnp.zeros((M, out_elems), jnp.float32)
            (recv, outputs, loss_sum, st), _ = jax.lax.scan(
                one_tick,
                (recv0, outputs0, jnp.zeros((1,), jnp.float32),
                 stflat[0]),
                jnp.arange(ticks),
            )
            loss = jax.lax.psum(loss_sum[0], axis) / M
            if self.dp > 1:
                # each data replica's loss is the mean over its local
                # rows; the global mean averages the replicas (equal
                # row counts — the microbatch spec splits evenly)
                loss = jax.lax.pmean(loss, self.data_axis)
                if training:
                    # BN statistics must agree across data replicas
                    # (weights do implicitly via identical updates)
                    st = jax.lax.pmean(st, self.data_axis)
            return loss, outputs[None], st[None]

        out_mb_spec = (
            P(self.axis, None, self.data_axis) if self.dp > 1 else P(self.axis)
        )
        param_spec = (
            P(self.axis, self.model_axis) if self.mp > 1 else P(self.axis)
        )
        return shard_map_compat(
            per_device,
            mesh=self.mesh,
            in_specs=(param_spec, P(self.axis), self._mb_spec, self._mb_spec),
            out_specs=(P(), out_mb_spec, P(self.axis)),
            check=False,
        )

    def _build_train_step(self, metric_update=None, mvs_example=None):
        """The jitted pipeline train step. With ``metric_update``, keras
        metric states accumulate INSIDE the compiled step on the last
        stage's predictions (r5, VERDICT r4 #5 — the r4 design returned
        per-step predictions as a gradient aux and updated metric states
        host-side: an O(dataset × output_dim) device→host transfer per
        epoch; now only the tiny metric-state pytree leaves the device,
        once per epoch)."""
        forward = self._forward(collect_outputs=metric_update is not None)
        optimizer = self.optimizer
        collect = metric_update is not None

        def loss_of(params, state, xm, ym):
            loss, outs, new_state = forward(params, state, xm, ym)
            # only the LAST stage's slice feeds the metric math —
            # reading the full stage-sharded [S, M, ·] buffer would
            # gather S× the needed bytes; when not collecting, nothing
            # is read and XLA DCEs the scan's outputs carry entirely
            # (code-review r4)
            aux = outs[self.S - 1] if collect else ()
            return loss, (new_state, aux)

        def base_step(params, state, opt_state, xm, ym):
            (loss, (new_state, outs)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params, state, xm, ym)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, new_state, opt_state, loss, outs

        state_sh = jax.tree.map(lambda l: l.sharding, self.opt_state)
        in_sh = (self._param_sh, self._stage_sh, state_sh,
                 self._mb_sh, self._mb_sh)
        out_sh = (self._param_sh, self._stage_sh, state_sh, self._rep_sh)

        if not collect:

            def step(params, state, opt_state, xm, ym):
                p, st, opt, loss, _ = base_step(params, state, opt_state,
                                                xm, ym)
                return p, st, opt, loss

            return jax.jit(
                step, in_shardings=in_sh, out_shardings=out_sh,
                donate_argnums=(0, 1, 2),
            )

        mvs_rep = jax.tree.map(lambda _: self._rep_sh, mvs_example)

        def step(params, state, opt_state, xm, ym, mvs, sw):
            p, st, opt, loss, outs = base_step(params, state, opt_state,
                                               xm, ym)
            # [M, dp·elems] → [batch, ...] rows in input order (replica
            # r's rows are the r-th contiguous chunk of each
            # microbatch); ym flattens identically, so rows align. All
            # inside the jit — no host round-trip.
            out_tail = tuple(self._shapes[-1].shape[1:])
            batch = self.M * self.mb_rows * self.dp
            y_pred_rows = outs.reshape(
                (self.M, self.dp, self.mb_rows) + out_tail
            ).reshape((batch,) + out_tail)
            y_rows = ym.reshape((batch,) + tuple(ym.shape[2:]))
            mvs = metric_update(mvs, y_rows, y_pred_rows, sw.reshape(batch))
            return p, st, opt, loss, mvs

        return jax.jit(
            step,
            in_shardings=in_sh + (mvs_rep, self._mb_sh),
            out_shardings=out_sh + (mvs_rep,),
            donate_argnums=(0, 1, 2),
        )

    # -- data shaping ----------------------------------------------------

    def _microbatches(self, x, n_rows):
        """[B, ...] → [M, mb, ...] in the input's own dtype (stage 0
        consumes this directly — integer token ids stay integer)."""
        mb = n_rows // self.M
        return np.asarray(x).reshape((self.M, mb) + x.shape[1:])

    # -- API -------------------------------------------------------------

    def fit(self, x, y, epochs: int = 1, batch_size: int = 32, verbose: int = 0,
            callbacks=None, metric_state=None, metric_update=None,
            on_epoch_metrics=None):
        """Mini-batch training; returns ``{'loss': [...]}`` per epoch.
        ``callbacks`` are ``cb(epoch, loss)`` at epoch boundaries.

        Compiled training metrics (r5, VERDICT r4 #5): pass
        ``metric_state`` (an initial state pytree),
        ``metric_update(mvs, y_rows, y_pred_rows, sw_rows) -> mvs``
        (traced INTO the jitted step — it sees the last stage's
        predictions on device, wrap-padded duplicate rows zero-weighted
        via ``sw_rows``), and ``on_epoch_metrics(mvs_host)`` (called at
        each epoch boundary, BEFORE ``callbacks``, with the host-read
        accumulated state, after which the state resets). Only the tiny
        state pytree crosses to host, once per epoch — predictions
        never do.

        ``batch_size`` is rounded up to a multiple of ``M`` (each
        microbatch keeps a fixed shape); the final short batch wrap-pads
        rows at full weight for the LOSS — duplicated rows slightly
        overweight, the same semantics as the DP runner's staged
        :func:`~elephas_tpu.worker.pad_to_batches` (the masked-tail
        exactness of :class:`~elephas_tpu.parallel.tensor.ShardedTrainer`
        would need weight-aware user loss_fns here). Metrics DO
        zero-weight the pads, like keras.
        """
        x = np.asarray(x)
        y = np.asarray(y)
        n = len(x)
        M = self.M
        grain = M * self.dp  # microbatch rows must split over data replicas
        batch_size = max(grain, (batch_size // grain) * grain)
        if self._shapes is None:
            # boundary shapes are per-DEVICE: the local microbatch slice
            mb_x = jnp.zeros((batch_size // grain,) + x.shape[1:], x.dtype)
            self._infer_shapes(mb_x)
        # the compiled pipeline is specialized to one microbatch shape
        batch_size = self.M * self.mb_rows * self.dp
        nb = max(1, int(np.ceil(n / batch_size)))
        idx = np.arange(nb * batch_size) % n
        collect = metric_update is not None
        train_step = self._get_train_step(metric_update, metric_state)
        mvs = None
        sw_full = sw_tail = None
        if collect:
            mvs = jax.tree.map(
                lambda l: put_global(np.asarray(l), self._rep_sh),
                metric_state,
            )
            # only TWO masks exist — all-ones, and the wrap-padded tail
            # batch; stage each ONCE instead of re-uploading per step
            # (code-review r5)
            sw_full = put_global(
                np.ones((M, batch_size // M), np.float32), self._mb_sh
            )
            tail = (
                ((nb - 1) * batch_size + np.arange(batch_size)) < n
            ).astype(np.float32).reshape(M, batch_size // M)
            sw_tail = (
                sw_full if tail.all() else put_global(tail, self._mb_sh)
            )

        history = {"loss": []}
        for epoch in range(epochs):
            losses = []
            for b in range(nb):
                rows = idx[b * batch_size : (b + 1) * batch_size]
                xm = self._microbatches(x[rows], batch_size)
                ym = np.asarray(y[rows]).reshape(
                    (M, batch_size // M) + y.shape[1:]
                )
                args = (
                    self.params, self.state, self.opt_state,
                    put_global(xm, self._mb_sh),
                    put_global(ym, self._mb_sh),
                )
                if collect:
                    (self.params, self.state, self.opt_state, loss,
                     mvs) = train_step(
                        *args, mvs,
                        sw_tail if b == nb - 1 else sw_full,
                    )
                else:
                    self.params, self.state, self.opt_state, loss = (
                        train_step(*args)
                    )
                losses.append(loss)
            if collect:
                mvs = self._drain_metrics(
                    mvs, metric_state, on_epoch_metrics
                )
            self._finish_epoch(
                history, losses, epoch, epochs, verbose, callbacks
            )
        return history

    def _get_train_step(self, metric_update=None, metric_state=None):
        """Get-or-build the jitted step, cached per metrics-or-not. The
        cache pins the exact ``metric_update`` closure it traced — a
        DIFFERENT closure (or state pytree) on a later fit rebuilds
        instead of silently serving the stale traced math
        (code-review r5)."""
        key = metric_update is not None
        cached = self._train_steps.get(key)
        if cached is not None and cached[1] is metric_update:
            return cached[0]
        step = self._build_train_step(metric_update, metric_state)
        self._train_steps[key] = (step, metric_update)
        return step

    def _drain_metrics(self, mvs, metric_state, on_epoch_metrics):
        """Epoch-boundary metric handoff shared by the staged and
        streamed fits: host-read the accumulated state, hand it to the
        caller, reset to the initial state on device."""
        on_epoch_metrics(
            jax.tree.map(lambda l: host_read(l, self.mesh), mvs)
        )
        return jax.tree.map(
            lambda l: put_global(np.asarray(l), self._rep_sh),
            metric_state,
        )

    def _finish_epoch(self, history, losses, epoch, epochs, verbose,
                      callbacks):
        """Shared staged/streamed epoch bookkeeping: history append,
        logging, callback dispatch."""
        epoch_loss = float(np.mean([np.asarray(l) for l in losses]))
        history["loss"].append(epoch_loss)
        if verbose:
            logger.info(
                "epoch %d/%d - loss %.4f", epoch + 1, epochs, epoch_loss
            )
        if callbacks:
            for cb in callbacks:
                cb(epoch, epoch_loss)
        return epoch_loss

    def fit_stream(self, stream, epochs: int = 1, verbose: int = 0,
                   callbacks=None, metric_state=None, metric_update=None,
                   on_epoch_metrics=None):
        """Streamed training over :class:`ShardedStream` blocks shaped
        ``[dp, steps, B, ...]`` — each step's global batch is the
        ``dp`` row-shards concatenated (``dp·B`` rows), microbatched
        through the ring like :meth:`fit`. Blocks never all live in
        device memory at once; the next block's host gather runs under
        the current block's compute (async dispatch).

        The stream's (per-worker) batch must divide into the ``M``
        microbatches — every step then carries the exact compiled shape
        with no mid-epoch padding (the stream wrap-pads short shard
        tails internally, matching the staged path's tail semantics).

        Compiled training metrics (r5, VERDICT r4 #7): same
        ``metric_state`` / ``metric_update`` / ``on_epoch_metrics``
        contract as :meth:`fit` — states accumulate on device through
        every streamed block and cross to host once per epoch.
        Stream-internal wrap-pad rows are zero-weighted in the METRICS
        via the stream's valid-row counts (ADVICE r5 — streamed and
        staged fits report identical epoch metrics); the loss keeps
        counting them at full weight, like the staged path.
        """
        from elephas_tpu.data.streaming import prefetch_blocks

        if stream.num_workers != self.dp:
            raise ValueError(
                f"stream has {stream.num_workers} shards for a "
                f"{self.dp}-replica data axis"
            )
        M, dp = self.M, self.dp
        if stream.batch_size % M:
            raise ValueError(
                f"stream batch_size={stream.batch_size} must be a "
                f"multiple of num_microbatches={M} (else every step "
                f"would pad duplicated rows, biasing gradients)"
            )
        if self._shapes is None:
            x1 = np.asarray(stream.x[0:1])
            self._infer_shapes(
                jnp.zeros(
                    (stream.batch_size // M,) + x1.shape[1:], x1.dtype
                )
            )
        need = M * self.mb_rows * dp
        if dp * stream.batch_size != need:
            raise ValueError(
                f"stream supplies {dp * stream.batch_size} rows/step but "
                f"the compiled pipeline takes {need} — match the stream "
                f"batch_size to the fit batch_size"
            )
        collect = metric_update is not None
        train_step = self._get_train_step(metric_update, metric_state)
        mvs = None
        sw_full = None
        sw_cache: dict[tuple, object] = {}
        if collect:
            mvs = jax.tree.map(
                lambda l: put_global(np.asarray(l), self._rep_sh),
                metric_state,
            )
            # metric weights zero the stream-internal wrap-pad rows so
            # streamed and staged fits report IDENTICAL epoch metrics
            # (ADVICE r5 — the loss still counts pads at full weight,
            # the documented staged-path semantics). Only a handful of
            # distinct masks exist (all-ones plus each shard-tail
            # pattern); each stages ONCE and is reused every epoch —
            # no per-step upload (code-review r5)
            sw_full = put_global(
                np.ones((M, need // M), np.float32), self._mb_sh
            )

        def _sw_for(gs: int):
            counts = stream.step_valid_counts(gs)
            if (counts >= stream.batch_size).all():
                return sw_full
            key = tuple(int(c) for c in counts)
            staged = sw_cache.get(key)
            if staged is None:
                # [dp, B] row validity flattens worker-major, exactly
                # like the step's x rows, then microbatches like them
                mask = (
                    np.arange(stream.batch_size)[None, :]
                    < counts[:, None]
                ).astype(np.float32)
                staged = put_global(
                    mask.reshape(M, need // M), self._mb_sh
                )
                sw_cache[key] = staged
            return staged

        history: dict[str, list[float]] = {"loss": []}
        for epoch in range(epochs):
            losses = []
            gs = 0  # global step index within the epoch
            for xb, yb, steps in prefetch_blocks(stream.blocks()):
                for t in range(steps):
                    xt, yt = xb[:, t], yb[:, t]  # [dp, B, ...]
                    x_flat = xt.reshape((need,) + xt.shape[2:])
                    y_flat = np.asarray(yt).reshape(
                        (need,) + yt.shape[2:]
                    )
                    xm = self._microbatches(x_flat, need)
                    ym = y_flat.reshape((M, need // M) + y_flat.shape[1:])
                    args = (
                        self.params, self.state, self.opt_state,
                        put_global(xm, self._mb_sh),
                        put_global(ym, self._mb_sh),
                    )
                    if collect:
                        (self.params, self.state, self.opt_state, loss,
                         mvs) = train_step(*args, mvs, _sw_for(gs))
                    else:
                        (self.params, self.state, self.opt_state,
                         loss) = train_step(*args)
                    losses.append(loss)
                    gs += 1
            if collect:
                mvs = self._drain_metrics(
                    mvs, metric_state, on_epoch_metrics
                )
            self._finish_epoch(
                history, losses, epoch, epochs, verbose, callbacks
            )
        return history

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        x = np.asarray(x)
        n = len(x)
        M = self.M
        grain = M * self.dp
        batch_size = max(grain, (batch_size // grain) * grain)
        if self._shapes is None:
            mb_x = jnp.zeros((batch_size // grain,) + x.shape[1:], x.dtype)
            self._infer_shapes(mb_x)
        batch_size = self.M * self.mb_rows * self.dp  # fixed microbatch shape
        if self._predict_fn is None:
            # inference program: moving statistics, no state writes
            forward = self._forward(
                collect_outputs=True, with_loss=False, training=False
            )
            out_mb_spec = (
                P(self.axis, None, self.data_axis)
                if self.dp > 1
                else P(self.axis)
            )
            self._predict_fn = jax.jit(
                lambda p, st, xm, ym: forward(p, st, xm, ym)[1],
                in_shardings=(self._param_sh, self._stage_sh, self._mb_sh,
                              self._mb_sh),
                out_shardings=NamedSharding(self.mesh, out_mb_spec),
            )
        out_shape = self._shapes[-1].shape  # local microbatch output
        nb = max(1, int(np.ceil(n / batch_size)))
        idx = np.arange(nb * batch_size) % n
        # targets unused without loss; dp rows so the data spec splits
        # (staged once — it never changes across batches)
        ym0_dev = put_global(np.zeros((M, self.dp), np.float32), self._mb_sh)
        outs = []
        for b in range(nb):
            rows = idx[b * batch_size : (b + 1) * batch_size]
            xm = self._microbatches(x[rows], batch_size)
            res = host_read(
                self._predict_fn(
                    self.params, self.state, put_global(xm, self._mb_sh),
                    ym0_dev,
                ),
                self.mesh,
            )
            # last stage's shard: [M, dp·elems_local]; replica r's rows
            # are the r-th contiguous chunk of each microbatch, so
            # [M, dp, mb_local, ...] flattens back to the input order
            outs.append(
                res[self.S - 1].reshape(
                    (M, self.dp, self.mb_rows) + out_shape[1:]
                ).reshape((batch_size,) + out_shape[1:])
            )
        return np.concatenate(outs)[:n]

    def _stage_from_host(self, host, s: int):
        """Unravel stage ``s`` from the gathered ``[S, P_max]`` (or
        ``[S, mp, P_max]``) host params. With a model axis the result is
        the LIST of per-rank shard pytrees — the caller re-assembles
        full variables per its slicing convention."""
        if self.mp > 1:
            return [
                self._unravels[s](
                    jnp.asarray(host[s, r][: self._p_sizes[s]])
                )
                for r in range(self.mp)
            ]
        return self._unravels[s](jnp.asarray(host[s][: self._p_sizes[s]]))

    def stage_weights_all(self) -> list:
        """Every stage's parameter pytree (per-rank pytrees under a
        model axis) from ONE gather of the stacked params (cross-process
        shards all-gather first) — weight syncs walk all stages, so
        per-stage gathers would move the full parameter set S times."""
        host = host_read(self.params, self.mesh)
        return [self._stage_from_host(host, s) for s in range(self.S)]

    def stage_weights(self, s: int):
        """Stage ``s``'s parameter pytree (host copy, unflattened;
        one gather, one unravel — loop via :meth:`stage_weights_all`
        to amortize the gather across stages)."""
        return self._stage_from_host(host_read(self.params, self.mesh), s)

    def stage_states_all(self) -> list:
        """Every stage's non-trainable state pytree from ONE gather of
        the stacked ``[S, N_max]`` state (see :meth:`stage_weights_all`)."""
        host = host_read(self.state, self.mesh)
        return [
            self._state_unravels[s](
                jnp.asarray(host[s][: self._s_sizes[s]])
            )
            for s in range(self.S)
        ]
