"""Expert parallelism — Mixture-of-Experts FFN over a mesh axis.

Absent from the reference (SURVEY.md §2a lists EP as not-implemented);
provided here as the TPU-native construction: experts are sharded over a
mesh axis (each device owns ``E/W`` experts' weights), tokens are routed
top-1 (Switch) or top-k (GShard) with a capacity bound and a
load-balance auxiliary loss, and the token↔expert exchange is
``lax.all_to_all`` over ICI — the canonical EP data path. The Keras
layer form (:class:`elephas_tpu.models.MoeFFN`) and the Switch
transformer builder live in :mod:`elephas_tpu.models.switch`.

Everything is dense and statically shaped (one-hot dispatch/combine
einsums, fixed capacity with overflow dropping) so the whole op lowers
through XLA with no ragged shapes; autodiff works end-to-end (all_to_all
is linear).

Call :func:`expert_parallel_ffn` INSIDE ``shard_map`` with tokens sharded
over the same axis as the experts. :func:`moe_ffn_reference` is the
single-device oracle used by the tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from elephas_tpu.parallel.mesh import axis_size_compat


def _topk_dispatch(x, gate_w, num_experts: int, capacity: int, k: int = 1):
    """Token → expert routing tensors (top-k, capacity-bounded).

    Returns ``(dispatch [T, E, C], combine [T, E, C], aux)``:

    - ``k=1``: Switch routing — each token goes to its argmax expert,
      combine-weighted by that expert's raw softmax prob.
    - ``k>1``: GShard-style — the top-k experts each process the token,
      combine weights are the top-k probs renormalized to sum to 1;
      first choices claim capacity slots before second choices.
    - ``aux``: the Switch §2.2 load-balance loss ``E · Σ_e f_e · P_e``
      (``f_e`` = fraction of tokens whose FIRST choice is ``e``, ``P_e``
      = mean router prob for ``e``) — differentiable through ``P``,
      minimized by a uniform router. Scale it and add to the task loss.

    Tokens beyond an expert's capacity are dropped (output zero — the
    residual connection around the MoE layer carries them, as in Switch).
    """
    if k > num_experts:
        raise ValueError(
            f"k={k} routing choices exceed num_experts={num_experts}"
        )
    logits = x @ gate_w  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # iterative top-k (k is tiny): argmax, mask, repeat
    choices = []  # [T] expert index per choice
    gates = []  # [T] raw prob per choice
    masked = probs
    for _ in range(k):
        expert = jnp.argmax(masked, axis=-1)
        choices.append(expert)
        gates.append(jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0])
        masked = masked * (1.0 - jax.nn.one_hot(expert, num_experts, dtype=probs.dtype))
    if k > 1:
        denom = sum(gates)
        gates = [g / jnp.maximum(denom, 1e-9) for g in gates]

    # routing math runs in int32 regardless of activation dtype: a
    # bfloat16 cumsum goes inexact past 256 tokens, silently corrupting
    # the capacity mask; only the final dispatch/combine cast to x.dtype
    dispatch = jnp.zeros((x.shape[0], num_experts, capacity), x.dtype)
    combine = jnp.zeros_like(dispatch)
    counts = jnp.zeros((num_experts,), jnp.int32)  # slots claimed so far
    for expert, gate in zip(choices, gates):
        onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)  # [T, E]
        # 0-based position of each token within its expert's queue (only
        # the token's own expert column is nonzero-capable), offset by
        # the slots earlier choices already claimed
        position = (
            jnp.cumsum(onehot, axis=0) * onehot - onehot + counts[None, :] * onehot
        )
        kept = (position < capacity) & (onehot > 0)
        rank = jnp.sum(jnp.where(kept, position, 0), axis=-1)  # [T] int32
        pos_onehot = jax.nn.one_hot(rank, capacity, dtype=x.dtype)  # [T, C]
        keep_mask = jnp.any(kept, axis=-1).astype(x.dtype)  # [T]
        d = (
            onehot.astype(x.dtype)[:, :, None]
            * pos_onehot[:, None, :]
            * keep_mask[:, None, None]
        )
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)

    first = jax.nn.one_hot(choices[0], num_experts, dtype=probs.dtype)
    f = jnp.mean(first, axis=0)  # fraction routed (first choice)
    p = jnp.mean(probs, axis=0)  # mean router prob
    aux = num_experts * jnp.sum(f * p)
    return dispatch, combine, aux


def _top1_dispatch(x, gate_w, num_experts: int, capacity: int):
    """Back-compat Switch top-1 routing: ``(dispatch, combine)``."""
    dispatch, combine, _ = _topk_dispatch(x, gate_w, num_experts, capacity, k=1)
    return dispatch, combine


def expert_parallel_ffn(
    x,
    gate_w,
    w1,
    b1,
    w2,
    b2,
    axis_name: str,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
    k: int = 1,
    return_aux: bool = False,
):
    """Top-k MoE FFN; call INSIDE ``shard_map``.

    Shapes (per device): ``x [T_local, D]``; ``gate_w [D, E_total]``
    (replicated); expert weights are the local shard —
    ``w1 [E_local, D, H]``, ``b1 [E_local, H]``, ``w2 [E_local, H, D]``,
    ``b2 [E_local, D]`` with ``E_total = W · E_local``. With
    ``return_aux`` also returns the load-balance loss (this shard's —
    ``pmean`` it across the axis if training on it).
    """
    w = axis_size_compat(axis_name)
    t_local, d = x.shape
    e_local = w1.shape[0]
    e_total = w * e_local
    # per-expert per-source-device slot budget (k assignments per token)
    capacity = max(1, int(k * t_local * capacity_factor / e_total))

    dispatch, combine, aux = _topk_dispatch(x, gate_w, e_total, capacity, k=k)

    # gather expert inputs locally, then all-to-all so each device
    # receives its own experts' tokens from every device
    expert_inputs = jnp.einsum("td,tec->ecd", x, dispatch)  # [E_total, C, D]
    expert_inputs = expert_inputs.reshape(w, e_local, capacity, d)
    expert_inputs = jax.lax.all_to_all(
        expert_inputs, axis_name, split_axis=0, concat_axis=0, tiled=False
    )  # [W_src, E_local, C, D]
    expert_inputs = jnp.moveaxis(expert_inputs, 0, 1).reshape(
        e_local, w * capacity, d
    )

    h = activation(
        jnp.einsum("ecd,edh->ech", expert_inputs, w1) + b1[:, None, :]
    )
    out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    # route results back to the devices that own the tokens
    out = jnp.moveaxis(
        out.reshape(e_local, w, capacity, d), 1, 0
    )  # [W_src, E_local, C, D]
    out = jax.lax.all_to_all(
        out, axis_name, split_axis=0, concat_axis=0, tiled=False
    )
    out = out.reshape(e_total, capacity, d)
    result = jnp.einsum("ecd,tec->td", out, combine)
    return (result, aux) if return_aux else result


def moe_ffn_reference(
    x,
    gate_w,
    w1,
    b1,
    w2,
    b2,
    capacity_factor: float = 1.25,
    activation=jax.nn.gelu,
    num_shards: int = 1,
    k: int = 1,
    return_aux: bool = False,
):
    """Single-device oracle with identical routing/capacity semantics.

    ``num_shards`` mirrors the EP run's token sharding: routing capacity
    is computed per shard, so with the same sharding factor the outputs
    of :func:`expert_parallel_ffn` match exactly. With ``return_aux``
    also returns the load-balance loss averaged over shards.
    """
    e_total = gate_w.shape[-1]
    shards = jnp.split(x, num_shards, axis=0)
    outs = []
    auxes = []
    for xs in shards:
        t_local = xs.shape[0]
        capacity = max(1, int(k * t_local * capacity_factor / e_total))
        dispatch, combine, aux = _topk_dispatch(xs, gate_w, e_total, capacity, k=k)
        auxes.append(aux)
        expert_inputs = jnp.einsum("td,tec->ecd", xs, dispatch)
        h = activation(
            jnp.einsum("ecd,edh->ech", expert_inputs, w1) + b1[:, None, :]
        )
        out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        outs.append(jnp.einsum("ecd,tec->td", out, combine))
    result = jnp.concatenate(outs, axis=0)
    if return_aux:
        return result, sum(auxes) / len(auxes)
    return result


def init_moe_params(
    key, d_model: int, d_hidden: int, num_experts: int, dtype=jnp.float32
):
    """Convenience initializer: (gate_w, w1, b1, w2, b2) for E experts."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale1 = (2.0 / d_model) ** 0.5
    scale2 = (2.0 / d_hidden) ** 0.5
    return (
        jax.random.normal(k1, (d_model, num_experts), dtype) * scale1,
        jax.random.normal(k2, (num_experts, d_model, d_hidden), dtype) * scale1,
        jnp.zeros((num_experts, d_hidden), dtype),
        jax.random.normal(k3, (num_experts, d_hidden, d_model), dtype) * scale2,
        jnp.zeros((num_experts, d_model), dtype),
    )
