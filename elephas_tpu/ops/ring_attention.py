"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §5
"long-context: absent"): sequence length there is bounded by one worker's
memory. Here the sequence dimension is sharded over a mesh axis; each
device keeps its query shard resident and the K/V shards rotate around
the ring via ``lax.ppermute`` (XLA lowers neighbor permutes onto ICI
neighbor links), with the online-softmax partial results merged by
log-sum-exp. Peak memory per device is O(S/W · D) and the permute of the
next chunk overlaps with compute of the current one under XLA's async
collectives — the blockwise/ring-attention construction.

Forward chunks run the Pallas flash kernel
(:mod:`elephas_tpu.ops.flash_attention`), so the hot op stays hand-tiled
for the MXU. The op carries a ``jax.custom_vjp`` whose backward is a
second ring pass: dK/dV accumulators rotate *with* their K/V chunks so
after W steps each device's gradients arrive back home — communication
stays neighbor-to-neighbor, memory stays O(S/W).

Causality across shards uses global positions: a chunk wholly in the
future is skipped, the diagonal chunk applies the in-kernel causal mask,
and past chunks run unmasked.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from elephas_tpu.ops.flash_attention import _flash_forward, NEG_INF
from elephas_tpu.parallel.mesh import axis_size_compat, shard_map_compat


def _merge(o1, lse1, o2, lse2):
    """Merge two attention partials by log-sum-exp of their normalizers."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def _ring_forward(q, k, v, axis_name, causal, scale, block_q, block_k, interpret):
    """Returns (out, lse) for the local shard; kv chunks rotate the ring.

    ``axis_index`` is taken ONLY on the causal path (where the switch
    consumes it): a dead ``axis_index`` in the non-causal scan body
    survives DCE into the lowered module, and XLA's SPMD partitioner
    refuses the orphaned ``PartitionId`` outside a manual region
    ("PartitionId instruction is not supported for SPMD
    partitioning...") — the root cause of the seed's non-causal
    SP failures (jit'd evaluate/predict under a sequence scope;
    regression-pinned in tests/test_sequence_parallel.py)."""
    w = axis_size_compat(axis_name)
    me = jax.lax.axis_index(axis_name) if causal else None
    bh, s_local, d = q.shape
    f32 = jnp.float32

    chunk = functools.partial(
        _flash_forward,
        scale=float(scale),
        block_q=min(block_q, s_local),
        block_k=min(block_k, k.shape[1]),
        interpret=interpret,
    )

    def full_chunk(q, kc, vc):
        return chunk(q, kc, vc, causal=False)

    def diag_chunk(q, kc, vc):
        return chunk(q, kc, vc, causal=True)

    def skip_chunk(q, kc, vc):
        return (
            jnp.zeros((bh, s_local, d), q.dtype),
            jnp.full((bh, s_local), NEG_INF, f32),
        )

    perm = [(i, (i + 1) % w) for i in range(w)]

    def step(carry, t):
        o, lse, kc, vc = carry
        if causal:
            src = (me - t) % w
            case = jnp.where(src == me, 1, jnp.where(src > me, 2, 0))
            oc, lsec = jax.lax.switch(
                case, (full_chunk, diag_chunk, skip_chunk), q, kc, vc
            )
        else:
            oc, lsec = full_chunk(q, kc, vc)
        o, lse = _merge(o.astype(f32), lse, oc.astype(f32), lsec)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc), None

    o0 = jnp.zeros((bh, s_local, d), f32)
    lse0 = jnp.full((bh, s_local), NEG_INF, f32)
    (o, lse, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v), jnp.arange(w))
    return o.astype(q.dtype), lse


def _chunk_grads(q, kc, vc, g, lse, delta, scale, mask):
    """Flash-backward recurrences for one (q-shard × kv-chunk) pair.

    ``lse``/``delta`` are the *global* log-sum-exp and rowsum(dO∘O) for the
    local q rows, so per-chunk probabilities p = exp(s − lse) are exact
    global attention weights. ``mask`` is the [S_q, S_k] validity mask.
    """
    f32 = jnp.float32
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(f32), kc.astype(f32),
        preferred_element_type=f32,
    ) * scale
    s = jnp.where(mask[None], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])  # zero where masked or skipped
    # rows whose *global* lse is NEG_INF (every position masked) would
    # otherwise get p = exp(NEG_INF - NEG_INF) = 1
    p = jnp.where(lse[..., None] <= NEG_INF * 0.5, 0.0, p)
    dp = jnp.einsum(
        "bqd,bkd->bqk", g.astype(f32), vc.astype(f32), preferred_element_type=f32
    )
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bqk,bkd->bqd", ds, kc.astype(f32)) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(f32)) * scale
    dv = jnp.einsum("bqk,bqd->bkd", p, g.astype(f32))
    return dq, dk, dv


def _ring_backward(axis_name, causal, scale, block_q, block_k, interpret,
                   residuals, g):
    q, k, v, out, lse = residuals
    w = axis_size_compat(axis_name)
    # causal-only, as in _ring_forward: a dead axis_index in the
    # non-causal body lowers to an orphaned PartitionId (see there)
    me = jax.lax.axis_index(axis_name) if causal else None
    bh, s_local, d = q.shape
    f32 = jnp.float32
    delta = jnp.sum(g.astype(f32) * out.astype(f32), axis=-1)  # [bh, S_local]

    rows = jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 1)

    perm = [(i, (i + 1) % w) for i in range(w)]

    def step(carry, t):
        dq, dk_rot, dv_rot, kc, vc = carry
        if causal:
            src = (me - t) % w
            # global positions: my rows at me*S, chunk cols at src*S
            mask = (rows + me * s_local) >= (cols + src * s_local)
        else:
            mask = jnp.ones((s_local, s_local), bool)
        dq_c, dk_c, dv_c = _chunk_grads(q, kc, vc, g, lse, delta, scale, mask)
        dq = dq + dq_c
        dk_rot = dk_rot + dk_c
        dv_rot = dv_rot + dv_c
        # kv and their gradient accumulators travel together; after w
        # steps the accumulators land back on the chunk's home device
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dk_rot = jax.lax.ppermute(dk_rot, axis_name, perm)
        dv_rot = jax.lax.ppermute(dv_rot, axis_name, perm)
        return (dq, dk_rot, dv_rot, kc, vc), None

    z = jnp.zeros((bh, s_local, d), f32)
    (dq, dk, dv, _, _), _ = jax.lax.scan(
        step, (z, z, z, k, v), jnp.arange(w)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_attention(q, k, v, axis_name, causal, scale, block_q, block_k, interpret):
    out, _ = _ring_forward(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale, block_q, block_k, interpret):
    out, lse = _ring_forward(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, block_q, block_k, interpret, residuals, g):
    return _ring_backward(
        axis_name, causal, scale, block_q, block_k, interpret, residuals, g
    )


_ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(
    q,
    k,
    v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Sequence-parallel attention; call INSIDE ``shard_map``/``pmap``.

    ``q/k/v``: the local sequence shard, ``[bh, S_local, D]`` (sequence
    axis sharded over ``axis_name``; batch*heads merged). Returns the
    local output shard ``[bh, S_local, D]``. Differentiable (custom
    ring-pass VJP).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    return _ring_attention(
        q, k, v, axis_name, bool(causal), float(scale),
        int(block_q), int(block_k), bool(interpret),
    )


def ring_attention_sharded(
    q,
    k,
    v,
    mesh,
    axis_name: str = "workers",
    causal: bool = False,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Global-array convenience wrapper: shards the sequence axis of
    ``[bh, S, D]`` inputs over ``mesh[axis_name]`` and runs
    :func:`ring_attention` under ``shard_map``."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        ring_attention,
        axis_name=axis_name,
        causal=causal,
        scale=scale,
        interpret=interpret,
    )
    spec = P(None, axis_name, None)
    sharded = shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False,
    )
    return sharded(q, k, v)
