"""Tiled online-softmax attention for the serving hot path (ISSUE 11).

The serving programs in :mod:`elephas_tpu.serving.kv_cache` and
:mod:`elephas_tpu.serving.paged_kv` historically materialized the full
``[B, H, C, S]`` score matrix per layer (``S`` = ``maxlen`` for the
fixed arena, the table-bucket span for the paged pool) and softmaxed
it — O(C·S) live memory per head and every K/V row of the span touched
per query. This module is the FlashAttention-style replacement
(Dao et al. 2022, the same construction ``ops/flash_attention.py``
hand-tiles for the MXU): K/V stream through fixed-size tiles, the
softmax runs online (running max ``m``, normalizer ``l``, accumulator
``acc``), and the score matrix never exists beyond one ``[B, H, C,
block_k]`` tile.

Unlike the Pallas kernel (which interprets — slowly — off-TPU), these
primitives are plain XLA: ``jnp`` einsums over statically sliced tiles,
unrolled at trace time. They fuse into the serving programs' jit on any
backend, the tile loop bounds are static (compiled shapes stay a closed
set), and causal prefill SKIPS the strictly-future tiles statically —
the O(T²)→O(T²/2) compute cut plus the O(T) memory cut are where the
long-prompt TTFT win comes from.

Numerics: online softmax evaluates the same mathematical softmax with a
different association order, so outputs match the naive oracle to float
tolerance, not bitwise. Temperature-0 tokens are argmax over logits
whose perturbation is ~1e-6 of the logit scale — token streams stay
exact on any model whose argmax is not a coin flip (the serving parity
suites assert exactly this, and the naive kernel remains selectable as
``attention="naive"``).

Fully-masked query rows (inactive slot lanes, padded chunk tails)
output exact zeros here, where the naive path produces NaN garbage —
both are fine (those lanes are never read), but zeros keep debugging
sane.
"""

from __future__ import annotations

NEG_INF = -1e30

DEFAULT_BLOCK = 128
SPAN_FLOOR = 64


def span_buckets(maxlen: int, floor: int = SPAN_FLOOR) -> tuple[int, ...]:
    """Power-of-two attention-span ladder ``[floor, 2·floor, ..]``
    capped at (and always including) ``maxlen`` — the fixed arena's
    analogue of the paged table-bucket ladder: flash decode/chunk
    programs compile once per span bucket and attend over
    ``cache[:, :span]`` instead of the full ``maxlen`` row, so a
    short-context steady state stops paying for the arena's worst case.
    The floor keeps tiny models at ONE bucket (one decode compile, the
    seed contract the serving tests pin)."""
    if maxlen <= 0:
        raise ValueError(f"maxlen must be positive, got {maxlen}")
    buckets, b = [], max(1, int(floor))
    while b < maxlen:
        buckets.append(b)
        b *= 2
    buckets.append(int(maxlen))
    return tuple(buckets)


def span_bucket_for(n: int, buckets) -> int:
    """Smallest span bucket covering ``n`` resident positions."""
    for b in buckets:
        if b >= n:
            return int(b)
    raise ValueError(
        f"span of {n} positions exceeds the largest bucket "
        f"{max(buckets)}"
    )


def _online_update(m, l, acc, s, vt):
    """One online-softmax accumulation step: fold the masked score
    tile ``s`` (``[..., bk]``, NEG_INF where invisible) and its value
    tile ``vt`` into the ``(m, l, acc)`` running state. The ``p``
    guard zeroes rows that have seen nothing but mask so far —
    ``exp(NEG_INF - NEG_INF)`` would otherwise accumulate phantom
    mass (same guard as the Pallas kernel)."""
    import jax.numpy as jnp

    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(m_new[..., None] <= NEG_INF * 0.5, 0.0, p)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + p.sum(axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum("bhck,bkhd->bhcd", p, vt)
    return m_new, l, acc


def flash_span_chunk(q, gk, gv, pos_mat, scale=None,
                     block_k: int = DEFAULT_BLOCK,
                     kv_dtype: str = "fp", kv_scales=None):
    """Tiled attention of chunk queries over a resident K/V span.

    ``q``: ``[B, H, C, Dh]`` queries at absolute positions ``pos_mat``
    (``[B, C]`` int32); ``gk``/``gv``: ``[B, S, H, Dh]`` — the cache
    span (fixed-arena rows sliced to a span bucket, or a paged table
    gather). Visibility is ``col <= pos`` (and ``col < S`` — callers
    guarantee every visible position sits inside the span). Returns
    ``[B, H, C, Dh]`` float32.

    The K/V axis streams in ``block_k`` tiles under a static python
    loop (``S`` is a bucketed compile-time constant, so the unroll is
    bounded by the span ladder); ragged final tiles take their natural
    smaller static shape — no padding pass. Peak intermediate is one
    ``[B, H, C, block_k]`` tile instead of the naive ``[B, H, C, S]``.

    Quantized spans (ISSUE 19): with ``kv_dtype`` ``"int8"``/``"int4"``
    the span arrives as int8 codes ``[B, S, H, Dhp]`` plus per-(pos,
    head) f32 ``kv_scales = (k_scales, v_scales)`` (``[B, S, H]``
    each), and each K/V tile dequantizes HERE — the tile loop is the
    seam, so fp rows never exist beyond one ``block_k`` tile.
    """
    import jax.numpy as jnp

    from elephas_tpu.serving.kv_quant import dequantize_rows

    f32 = jnp.float32
    B, H, C, Dh = q.shape
    S = int(gk.shape[1])
    if scale is None:
        scale = Dh ** -0.5
    q = q.astype(f32)
    m = jnp.full((B, H, C), NEG_INF, f32)
    l = jnp.zeros((B, H, C), f32)
    acc = jnp.zeros((B, H, C, Dh), f32)
    for j0 in range(0, S, block_k):
        j1 = min(S, j0 + block_k)
        if kv_dtype == "fp":
            kt = gk[:, j0:j1].astype(f32)  # [B, bk, H, Dh]
            vt = gv[:, j0:j1].astype(f32)
        else:
            ks, vs = kv_scales
            kt = dequantize_rows(
                gk[:, j0:j1], ks[:, j0:j1], kv_dtype, Dh
            )
            vt = dequantize_rows(
                gv[:, j0:j1], vs[:, j0:j1], kv_dtype, Dh
            )
        s = jnp.einsum("bhcd,bkhd->bhck", q, kt) * scale
        vis = (
            jnp.arange(j0, j1)[None, None, None, :]
            <= pos_mat[:, None, :, None]
        )
        s = jnp.where(vis, s, NEG_INF)
        m, l, acc = _online_update(m, l, acc, s, vt)
    return acc / jnp.where(l == 0.0, 1.0, l)[..., None]


def flash_span_decode(q, gk, gv, positions, scale=None,
                      block_k: int = DEFAULT_BLOCK,
                      kv_dtype: str = "fp", kv_scales=None):
    """One-row decode attention over a K/V span: ``q`` ``[B, H, Dh]``
    at per-slot ``positions`` ``[B]``, ``gk``/``gv`` ``[B, S, H, Dh]``.
    Returns ``[B, H, Dh]`` float32. The single query row rides
    :func:`flash_span_chunk` with ``C == 1`` — one attention variant
    to keep correct, and the block-span read (``S`` = a span/table
    bucket, not ``maxlen``) is where decode's win lives. Quantized
    spans pass ``kv_dtype``/``kv_scales`` through to the tile loop."""
    out = flash_span_chunk(
        q[:, :, None], gk, gv, positions[:, None], scale=scale,
        block_k=block_k, kv_dtype=kv_dtype, kv_scales=kv_scales,
    )
    return out[:, :, 0]


def flash_causal_prefill(q, k, v, scale=None,
                         block_q: int = DEFAULT_BLOCK,
                         block_k: int = DEFAULT_BLOCK):
    """Causal self-attention of a whole prompt bucket from position 0:
    ``q``/``k``/``v`` ``[B, H, S, Dh]``, returns ``[B, H, S, Dh]``
    float32.

    Both axes tile; a K/V tile strictly in a query tile's future
    (``j0 >= i1``) is SKIPPED at trace time — the static causal
    schedule computes ~half the naive FLOPs, and only the
    diagonal-crossing tile pays a mask at all. This is the program
    behind cold full-bucket prefill, where the O(S²) term actually
    bites."""
    import jax.numpy as jnp

    f32 = jnp.float32
    B, H, S, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    q = q.astype(f32)
    out = []
    for i0 in range(0, S, block_q):
        i1 = min(S, i0 + block_q)
        qt = q[:, :, i0:i1]  # [B, H, bq, Dh]
        bq = i1 - i0
        m = jnp.full((B, H, bq), NEG_INF, f32)
        l = jnp.zeros((B, H, bq), f32)
        acc = jnp.zeros((B, H, bq, Dh), f32)
        for j0 in range(0, i1, block_k):  # j0 >= i1 is wholly future
            j1 = min(S, j0 + block_k)
            kt = jnp.moveaxis(k[:, :, j0:j1], 1, 2).astype(f32)
            vt = jnp.moveaxis(v[:, :, j0:j1], 1, 2).astype(f32)
            s = jnp.einsum("bhcd,bkhd->bhck", qt, kt) * scale
            if j1 > i0:  # diagonal-crossing tile: mask the future half
                visible = (
                    jnp.arange(j0, j1)[None, :]
                    <= jnp.arange(i0, i1)[:, None]
                )
                s = jnp.where(visible[None, None], s, NEG_INF)
            m, l, acc = _online_update(m, l, acc, s, vt)
        out.append(acc / jnp.where(l == 0.0, 1.0, l)[..., None])
    return jnp.concatenate(out, axis=2)
