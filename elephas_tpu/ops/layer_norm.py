"""Fused LayerNorm — a one-pass Pallas row kernel, with an honest
measurement story.

Motivation: the r4/r5 transformer traces bill ~30% of device time to
layernorm-class fusions (``divide_subtract_fusion``,
``multiply_reduce_fusion``) plus bf16↔f32 convert traffic. This kernel
does the whole forward — f32 statistics, normalize, affine — in ONE
pass per row block (bf16 in/out, converts in registers), and the whole
backward (dx AND dgamma/dbeta, accumulated in VMEM scratch across the
sequential row grid) in one more pass.

MEASURED OUTCOME (r5, v5e, d=1024 preset — VERDICT r4 #3a): parity,
not a win. End-to-end transformer bench: 220.4–221.4k tok/s with this
kernel vs 221.9–223.0k with stock ``keras.layers.LayerNormalization``
(same session); per-op A/B agrees (~2.5 ms fwd+bwd either way at
[32768, 1024]). Both implementations sit at the platform's REALIZED
elementwise bandwidth (~50–100 GB/s on this chip class), i.e. the
layernorm share of the trace is a bandwidth bound, not a fusion
deficiency — which is why the in-tree transformer builders keep the
stock layer, and why raising arithmetic intensity (d_model 2048) lifts
the same code path from ~35% to 47.2% MFU. The op stays exported
(``elephas_tpu.models.FusedLayerNorm``) for shapes where one fused
pass wins.

The op carries a ``jax.custom_vjp`` and runs in Pallas interpreter
mode off-TPU (tests), one code path — same structure as
:mod:`elephas_tpu.ops.flash_attention`. Reference parity: the
reference has no norm op of its own (keras layers); this is a
TPU-native extension (SURVEY.md §5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# 256 measured best on v5e (512 can't win anyway: any n divisible by
# 512 matches 256 first, and the end-to-end sweep showed no gain)
_ROW_BLOCKS = (256, 128, 64, 32, 16, 8, 4, 2, 1)


def _row_block(n: int) -> int:
    for b in _ROW_BLOCKS:
        if n % b == 0:
            return b
    return 1


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)  # [BR, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(
        jnp.float32
    )
    o_ref[:] = y.astype(o_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, dy_ref, mean_ref, rstd_ref,
                dx_ref, dg_ref, db_ref, dg_acc, db_acc):
    # ONE pass produces dx AND the parameter grads: dgamma/dbeta
    # accumulate in VMEM scratch across the (sequential) row grid and
    # write out on the last step — no second XLA pass re-reading x
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_acc[:] = jnp.zeros_like(dg_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = (x - mean_ref[:]) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = ((wdy - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)
    dg_acc[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[:] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finalize():
        dg_ref[:] = dg_acc[:]
        db_ref[:] = db_acc[:]


def _fwd_call(x2, gamma, beta, eps, interpret):
    n, d = x2.shape
    br = _row_block(n)
    grid = (n // br,)
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma[None], beta[None])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm2(x2, gamma, beta, eps, interpret):
    y, _m, _r = _fwd_call(x2, gamma, beta, eps, interpret)
    return y


def _ln_fwd_rule(x2, gamma, beta, eps, interpret):
    y, mean, rstd = _fwd_call(x2, gamma, beta, eps, interpret)
    return y, (x2, gamma, mean, rstd)


def _ln_bwd_rule(eps, interpret, residuals, dy):
    from jax.experimental.pallas import tpu as pltpu

    x2, gamma, mean, rstd = residuals
    n, d = x2.shape
    br = _row_block(n)
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        grid=(n // br,),
        in_specs=[row_spec, vec_spec, row_spec, stat_spec, stat_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma[None], dy, mean, rstd)
    return dx, dg[0].astype(gamma.dtype), db[0].astype(gamma.dtype)


_layer_norm2.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def layer_norm(x, gamma, beta, eps: float = 1e-6,
               interpret: bool | None = None):
    """LayerNormalization over the LAST axis of ``x`` (any leading
    shape), keras-equivalent math: f32 mean/variance statistics, affine
    ``gamma``/``beta``, output in ``x``'s dtype. One fused pass forward
    and one for ``dx`` backward."""
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    lead = x.shape[:-1]
    d = x.shape[-1]
    n = 1
    for s in lead:
        n *= int(s)
    y = _layer_norm2(
        x.reshape(n, d), gamma, beta, float(eps), bool(interpret)
    )
    return y.reshape(x.shape)
