"""elephas_tpu — TPU-native distributed deep learning for Keras.

A from-scratch rebuild of the capabilities of the `elephas` reference
(Keras-on-Spark data-parallel training; see SURVEY.md) designed TPU-first
on JAX/XLA:

- Per-worker TensorFlow/CUDA execution becomes a single ``jax.jit``-compiled
  Keras-3 (jax backend) train program per epoch, sharded over a
  ``jax.sharding.Mesh`` worker axis via ``shard_map`` — zero Python in the
  hot loop.
- The reference's pickle-over-HTTP/TCP parameter server
  (``[U] elephas/parameter/``) is replaced in the hot path by XLA
  collectives (``lax.pmean``) over ICI/DCN. Parameter-server classes are
  still provided (``elephas_tpu.parameter``) for API parity and for
  cross-host weight stores over DCN.
- RDD partitions (``[U] elephas/utils/rdd_utils.py``) map onto mesh workers;
  a lightweight ``SparkContext``/``Rdd`` shim supplies the reference's data
  API without a JVM.

Public surface mirrors the reference (``[U] elephas/spark_model.py``,
``ml_model.py``, ``hyperparam.py``): ``SparkModel`` and ``SparkMLlibModel``
here; ``ElephasEstimator``/``ElephasTransformer`` in
``elephas_tpu.ml_model`` and ``HyperParamModel`` in
``elephas_tpu.hyperparam``.
"""

import os
import sys

# Keras must run on the jax backend before anything imports keras.
os.environ.setdefault("KERAS_BACKEND", "jax")

# keras locks its backend at import; under any other backend every
# compiled path here would fail later with an opaque tracer error —
# fail loud and early instead. Two ways to get it wrong: keras already
# imported under another backend, or KERAS_BACKEND explicitly exported
# to something else with keras not yet imported.
_backend = (
    sys.modules["keras"].backend.backend()
    if "keras" in sys.modules
    else os.environ["KERAS_BACKEND"]
)
if _backend != "jax":
    raise ImportError(
        f"elephas_tpu requires the Keras jax backend, but the active "
        f"backend is {_backend!r}. Import elephas_tpu before keras and "
        f"leave KERAS_BACKEND unset, or set KERAS_BACKEND=jax."
    )

__version__ = "0.6.0"

from elephas_tpu.spark_model import (  # noqa: E402,F401
    SparkModel,
    SparkMLlibModel,
    load_spark_model,
)
from elephas_tpu.ml_model import (  # noqa: E402,F401
    ElephasEstimator,
    ElephasTransformer,
    load_ml_estimator,
    load_ml_transformer,
)
from elephas_tpu.hyperparam import HyperParamModel  # noqa: E402,F401

__all__ = [
    "SparkModel",
    "SparkMLlibModel",
    "load_spark_model",
    "ElephasEstimator",
    "ElephasTransformer",
    "load_ml_estimator",
    "load_ml_transformer",
    "HyperParamModel",
    "ShardedTrainer",
    "GPipeTrainer",
    "SequenceShardedTrainer",
    "__version__",
]


def __getattr__(name):
    # heavier TPU-native extensions resolve lazily so the parity surface
    # stays import-light
    if name == "ShardedTrainer":
        from elephas_tpu.parallel.tensor import ShardedTrainer

        return ShardedTrainer
    if name == "GPipeTrainer":
        from elephas_tpu.ops.pipeline import GPipeTrainer

        return GPipeTrainer
    if name == "SequenceShardedTrainer":
        from elephas_tpu.parallel.sequence import SequenceShardedTrainer

        return SequenceShardedTrainer
    raise AttributeError(name)
