"""Compiled distributed training programs — the SparkWorker equivalent.

Reference surface: ``[U] elephas/worker.py`` — ``SparkWorker`` (synchronous)
and ``AsynchronousSparkWorker`` rebuild the Keras model inside each Spark
executor, run local ``model.fit`` over their RDD partition, and exchange
weights either by driver-side averaging or through a pickle-over-HTTP/TCP
parameter server (SURVEY.md §3.1/3.2).

TPU-first redesign: there are no worker processes. A whole training epoch
for *all* workers is one XLA program — ``jax.jit(shard_map(...))`` over a
1-D ``('workers',)`` mesh:

- each worker's parameters/optimizer state live as one shard of a stacked
  ``[W, ...]`` array (its leading-axis slice), so "per-worker model
  replicas" are just a sharded pytree;
- the per-batch loop is ``lax.scan`` — no Python, no dispatch, no pickle;
- weight synchronization is ``lax.pmean`` compiled into the program,
  riding ICI/DCN instead of the reference's Flask/socket round-trips.

Mode semantics (see SURVEY.md §2a):

- ``synchronous``: gradients are ``pmean``-ed across workers every step
  (replicas stay bit-identical — classic SPMD data parallelism; the
  north-star path). The reference's coarser "train whole fit locally,
  average once" behavior is available as ``frequency='fit'``.
- ``asynchronous``: workers take independent local steps; weights (and
  float non-trainable state) are ``pmean``-averaged at each ``frequency``
  boundary (``'batch'`` or ``'epoch'``) — local-SGD with a staleness bound
  of one period, the honest SPMD mapping of the reference's
  parameter-server staleness.
- ``hogwild``: same schedule as ``asynchronous``. The reference's only
  difference is eliding a server-side lock (a *race*, not an algorithm);
  on gang-scheduled TPUs there is no lock to elide, so the two modes are
  computationally identical here. The semantic difference is documented
  rather than simulated.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
from concurrent.futures import Future
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elephas_tpu import telemetry
from elephas_tpu.parallel.mesh import shard_map_compat
from elephas_tpu.utils import sockets


def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
    return shard_map_compat(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check=check_rep
    )

logger = logging.getLogger(__name__)

MODES = ("synchronous", "asynchronous", "hogwild")
FREQUENCIES = ("epoch", "batch", "fit")


def _pmean_floats(tree, axis_name: str):
    """pmean float leaves; pass integer leaves (counters, seeds) through."""
    return jax.tree.map(
        lambda a: jax.lax.pmean(a, axis_name)
        if jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        tree,
    )


def _unstack0(tree):
    return jax.tree.map(lambda a: a[0], tree)


def _stack0(tree):
    return jax.tree.map(lambda a: a[None], tree)


def pad_to_batches(x: np.ndarray, num_batches: int, batch_size: int) -> np.ndarray:
    """Wrap-pad rows so ``x`` reshapes to ``[num_batches, batch_size, ...]``.

    Wrap-around duplication (rather than zero-pad + masking) keeps the
    training program mask-free; duplicated samples slightly overweight a
    few rows in the last partial batch, matching the spirit of the
    reference's per-worker ``model.fit`` which also sees a ragged final
    batch.
    """
    n = len(x)
    total = num_batches * batch_size
    if n == 0:
        raise ValueError("cannot pad an empty partition")
    idx = np.arange(total) % n
    return x[idx].reshape((num_batches, batch_size) + x.shape[1:])


def stack_worker_batches(
    partitions: list[tuple[np.ndarray, np.ndarray]],
    batch_size: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Partition arrays → ``x[W, nb, B, ...]``, ``y[W, nb, B, ...]``.

    Also returns per-worker true sample counts and the common batch count
    (the max over workers — shorter partitions wrap).
    """
    counts = np.array([len(x) for x, _ in partitions])
    nb = max(1, int(np.ceil(counts.max() / batch_size)))
    xs = np.stack([pad_to_batches(x, nb, batch_size) for x, _ in partitions])
    ys = np.stack([pad_to_batches(y, nb, batch_size) for _, y in partitions])
    return xs, ys, counts, nb


class KerasIntrospection:
    """Loss/metric introspection over a compiled Keras model — shared by
    :class:`MeshRunner` (DP over a ``('workers',)`` mesh) and
    :class:`~elephas_tpu.parallel.tensor.ShardedTrainer` (DP×TP over a
    ``('data', 'model')`` mesh). Subclasses provide ``self.model``."""

    model = None  # set by subclass __init__

    def _host_read(self, leaf) -> np.ndarray:
        """Full host value of a (possibly sharded) device leaf —
        :func:`elephas_tpu.parallel.mesh.host_read` over ``self.mesh``
        (cross-process shards all-gather in XLA first)."""
        from elephas_tpu.parallel.mesh import host_read

        return host_read(leaf, self.mesh)

    def _output_names(self) -> list[str]:
        names = list(getattr(self.model, "output_names", []) or [])
        if not names:
            n_out = len(getattr(self.model, "outputs", None) or [1])
            names = [f"output_{i}" for i in range(n_out)]
        return names

    def _single_loss_fn(self, loss):
        """One loss spec → per-sample (unreduced) callable."""
        import keras

        if isinstance(loss, str):
            fn = keras.losses.get(loss)  # plain function: per-sample values
        elif isinstance(loss, keras.losses.Loss):
            fn = loss.call  # unreduced
        elif callable(loss):
            fn = loss
        else:
            raise ValueError(f"unsupported loss spec {loss!r}")

        def aligned(y, y_pred):
            # keras Loss.__call__ squeezes/expands rank-mismatched targets
            # (e.g. binary y [B] vs y_pred [B,1]); raw loss fns don't
            y = jnp.asarray(y)
            if y.ndim == y_pred.ndim - 1 and y_pred.shape[-1] == 1:
                y = y[..., None]
            return fn(y, y_pred)

        return aligned

    def _per_sample_loss_fn(self):
        """Per-sample loss over possibly multi-output models.

        Returns ``fn(y, y_pred) -> dict`` with key ``'loss'`` ([B] total,
        loss-weighted like ``keras.Model.compute_loss``) plus
        ``'<output>_loss'`` per output when the model has several
        (matching ``keras.Model.evaluate``'s reporting).
        """
        loss = self.model.loss
        names = self._output_names()
        weights = getattr(
            getattr(self.model, "_compile_loss", None), "_user_loss_weights", None
        )
        # weight-by-output-name first, then select: keeps list weights
        # aligned to outputs even when a dict loss omits some of them
        if isinstance(weights, dict):
            weight_of = {n: float(weights.get(n, 1.0)) for n in names}
        elif weights is not None:
            weight_of = {n: float(w) for n, w in zip(names, weights)}
        else:
            weight_of = {n: 1.0 for n in names}

        if isinstance(loss, (list, tuple)):
            specs = list(loss)
        elif isinstance(loss, dict):
            missing = [n for n in loss if n not in names]
            if missing:
                raise ValueError(
                    f"loss dict keys {missing} do not match outputs {names}"
                )
            specs = [loss[n] for n in names if n in loss]
            names = [n for n in names if n in loss]
        else:
            fn = self._single_loss_fn(loss)
            return lambda y, y_pred: {"loss": fn(y, y_pred)}

        fns = [self._single_loss_fn(s) for s in specs]
        ws = [weight_of[n] for n in names]

        def multi(y, y_pred):
            ys = list(y) if isinstance(y, (list, tuple)) else [y]
            yps = list(y_pred) if isinstance(y_pred, (list, tuple)) else [y_pred]
            out = {}
            total = 0.0
            for name, f, w, yi, ypi in zip(names, fns, ws, ys, yps):
                values = f(yi, ypi)
                out[f"{name}_loss"] = values
                total = total + w * values
            out["loss"] = total
            return out

        return multi

    def _unwrapped_metrics(self, x_sample, y_sample):
        """Compiled metric entries: ``(metric, output_index, reported_name)``.

        CompileMetrics mishandles ``sample_weight`` in its count update
        (observed keras 3.13), so the underlying metrics are used directly
        for exact padded-batch aggregation. For multi-output models the
        per-output nesting (``CompileMetrics._flat_metrics``) supplies the
        output index and the ``<output>_<metric>`` reported name keras
        uses. CompileMetrics (and its inner metrics) build lazily — force
        variable creation with one tiny host-side update, then reset.
        """
        yp = self.model(x_sample[:1], training=False)
        multi = isinstance(yp, (list, tuple))
        names = self._output_names()

        def y_head(y):
            return jax.tree.map(lambda a: np.asarray(a)[:1], y)

        # loss trackers ('loss' plus per-output '<name>_loss' Means) are
        # computed by the evaluator's own per-sample loss path, not as
        # y/y_pred metrics
        loss_tracker_names = set(self._loss_keys())
        out = []
        for m in self.model.metrics:
            if m.name in loss_tracker_names:
                continue
            is_compile = type(m).__name__ == "CompileMetrics"
            if is_compile and not getattr(m, "metrics", None):
                m.update_state(y_head(y_sample), yp)
                m.reset_state()
            per_output = getattr(m, "_flat_metrics", None)
            if is_compile and multi and per_output is not None:
                for i, bucket in enumerate(per_output):
                    for mm in getattr(bucket, "metrics", None) or []:
                        out.append((mm, i, f"{names[i]}_{mm.name}"))
            elif is_compile and getattr(m, "metrics", None):
                out.extend((mm, 0, mm.name) for mm in m.metrics)
            else:
                out.append((m, 0, m.name))
        for mm, i, _name in out:
            if not mm.variables:
                yi = y_sample[i] if multi else y_sample
                ypi = yp[i] if multi else yp
                mm.update_state(np.asarray(yi)[:1], ypi)
                mm.reset_state()
        return out

    def _loss_keys(self) -> list[str]:
        """Reported loss keys, in keras order: total first, then per-output."""
        loss = self.model.loss
        names = self._output_names()
        if isinstance(loss, dict):
            return ["loss"] + [f"{n}_loss" for n in names if n in loss]
        if isinstance(loss, (list, tuple)):
            return ["loss"] + [f"{n}_loss" for n in names]
        return ["loss"]

    def _zero_metric_state(self, metric_objects):
        """Fresh metric variables as host zeros."""
        return [
            [np.zeros(v.shape, v.dtype) for v in m.variables]
            for m, _i, _n in metric_objects
        ]

    def _history_from_metrics(self, history, metric_objects, mvs):
        """Append one epoch's metric results to a history dict."""
        for (m, _i, name), mv in zip(metric_objects, mvs):
            res = m.stateless_result(mv)
            if isinstance(res, dict):
                for k, v in res.items():
                    history.setdefault(k, []).append(float(np.asarray(v)))
            else:
                history.setdefault(name, []).append(float(np.asarray(res)))

    @staticmethod
    def _broadcast_sw(sw, y):
        """Per-ROW sample weights ``[B]`` gain trailing singleton axes
        so they broadcast against rank>1 targets — a sequence model's
        per-token loss/metric is ``[B, S]`` and a flat ``[B]`` weight
        fails jnp broadcasting (found driving an LM through the L5
        sequence-parallel route, r4)."""
        y_rank = getattr(y, "ndim", 1)
        if sw is not None and getattr(sw, "ndim", 1) == 1 and y_rank > 1:
            return sw.reshape(sw.shape + (1,) * (y_rank - 1))
        return sw

    def _stateless_loss(self, tv, ntv, x, y, sample_weight=None):
        """Forward pass + total training loss with differentiable
        add_loss/regularizer contributions.

        ``stateless_call(return_losses=True)`` collects add_loss values
        AND regularization losses computed from the TRACED variables;
        ``compute_loss`` must read those via ``_losses_override`` —
        keras's own jax train_step pattern. Calling ``compute_loss``
        bare would fold in regularizers recomputed from concrete
        variable state: right value, zero gradient.

        Returns ``(y_pred, ntv2, total_loss, extras_sum)`` where
        ``extras_sum`` is the (differentiable) sum of the add_loss /
        regularizer terms inside ``total_loss``.
        """
        model = self.model
        y_pred, ntv2, losses = model.stateless_call(
            tv, ntv, x, training=True, return_losses=True
        )
        extras = sum(losses) if losses else 0.0
        if losses:
            model._losses_override.clear()
            model._losses_override = list(losses)
        try:
            kwargs = {}
            if sample_weight is not None:
                kwargs["sample_weight"] = self._broadcast_sw(
                    sample_weight, y
                )
            total = model.compute_loss(x=x, y=y, y_pred=y_pred, **kwargs)
        finally:
            if losses:
                model._losses_override.clear()
        return y_pred, ntv2, total, extras


class MeshRunner(KerasIntrospection):
    """Owns the compiled train/eval/predict programs for one Keras model.

    The model must be compiled (optimizer/loss/metrics) and built. All
    programs are cached per (static-shape) signature, so repeated ``fit``
    epochs reuse one executable.
    """

    def __init__(self, model, mode: str, frequency: str, mesh: Mesh):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if frequency not in FREQUENCIES:
            raise ValueError(
                f"frequency must be one of {FREQUENCIES}, got {frequency!r}"
            )
        self.model = model
        self.mode = mode
        self.frequency = frequency
        self.mesh = mesh
        self.num_workers = mesh.devices.size
        self._epoch_fn = None
        self._eval_fn = None
        self._predict_fn = None
        model.optimizer.build(model.trainable_variables)

    # -- state plumbing ------------------------------------------------

    def _host_state(self):
        tv = [np.asarray(v.value) for v in self.model.trainable_variables]
        ntv = [np.asarray(v.value) for v in self.model.non_trainable_variables]
        ov = [np.asarray(v.value) for v in self.model.optimizer.variables]
        return tv, ntv, ov

    def _local_worker_indices(self) -> list[int]:
        """Mesh positions whose device belongs to this process (multi-host:
        the workers whose data/state this process stages)."""
        pid = jax.process_index()
        return [
            i
            for i, d in enumerate(self.mesh.devices.flat)
            if d.process_index == pid
        ]

    def _device_state(self, stacked: bool = True):
        """Current model state, replicated to ``[W, ...]`` worker shards.

        Multi-host: each process materializes only its addressable
        workers' slices (``jax.make_array_from_process_local_data``); the
        global array spans the pod without any host holding all of it.
        """
        W = self.num_workers
        sharding = NamedSharding(self.mesh, P("workers"))
        tv, ntv, ov = self._host_state()
        multiproc = jax.process_count() > 1
        n_local = len(self._local_worker_indices()) if multiproc else W

        def rep(leaf):
            local = np.broadcast_to(leaf[None], (n_local,) + leaf.shape)
            if multiproc:
                return jax.make_array_from_process_local_data(
                    sharding, local, (W,) + leaf.shape
                )
            return jax.device_put(local, sharding)

        return (
            [rep(l) for l in tv],
            [rep(l) for l in ntv],
            [rep(l) for l in ov],
        )

    def _shard_data(self, arr: np.ndarray):
        """Worker-shard a GLOBAL ``[W, ...]`` host array (multi-host:
        slice out this process's workers first)."""
        if jax.process_count() > 1:
            arr = arr[np.asarray(self._local_worker_indices())]
        return self._shard_local_data(arr)

    def _shard_local_data(self, local: np.ndarray):
        """Worker-shard an array of which this process holds ONLY its
        local workers' slices (``[W_local, ...]``) — the streaming path
        gathers local rows only, so there is no global array to slice."""
        sharding = NamedSharding(self.mesh, P("workers"))
        if jax.process_count() > 1:
            global_shape = (self.num_workers,) + local.shape[1:]
            return jax.make_array_from_process_local_data(
                sharding, local, global_shape
            )
        return jax.device_put(local, sharding)

    @staticmethod
    def _worker_slice(leaf, index: int = 0):
        """One worker's slice of a ``[W, ...]``-sharded leaf. Multi-host,
        leaves span non-addressable devices — read the first local shard
        instead (all replicas agree post-sync)."""
        if getattr(leaf, "is_fully_addressable", True):
            return np.asarray(leaf[index])
        return np.asarray(leaf.addressable_shards[0].data)[0]

    def _write_back(self, tv, ntv, ov=None):
        """Worker-0 slice → model variables (all replicas agree post-sync)."""
        for var, leaf in zip(self.model.trainable_variables, tv):
            var.assign(self._worker_slice(leaf))
        for var, leaf in zip(self.model.non_trainable_variables, ntv):
            var.assign(self._worker_slice(leaf))
        if ov is not None:
            for var, leaf in zip(self.model.optimizer.variables, ov):
                var.assign(self._worker_slice(leaf))

    # -- loss helpers --------------------------------------------------

    def _loss_and_updates(self, tv, ntv, x, y):
        y_pred, ntv2, loss, _extras = self._stateless_loss(tv, ntv, x, y)
        return loss, (ntv2, y_pred)

    # -- training ------------------------------------------------------

    def _build_epoch_fn(self, metric_objects=None):
        """One whole training epoch as a single XLA program.

        With ``metric_objects`` (from :meth:`_unwrapped_metrics`), metric
        states thread through the batch scan exactly as keras accumulates
        training metrics over an epoch, then ``psum`` across workers
        (Mean-type states are additive) — history gains the compiled
        metrics with zero extra forward passes.
        """
        mode, frequency = self.mode, self.frequency
        grad_fn = jax.value_and_grad(self._loss_and_updates, has_aux=True)
        optimizer = self.model.optimizer
        metric_objects = metric_objects or []

        def per_worker(tv, ntv, ov, mvs, xb, yb):
            # tv/ntv/ov arrive as the worker's [1, ...] shard; mvs arrive
            # whole (replicated zeros) and leave whole (psum'd)
            tv, ntv, ov = _unstack0(tv), _unstack0(ntv), _unstack0(ov)
            xb, yb = xb[0], yb[0]

            def step(carry, batch):
                tv, ntv, ov, mvs = carry
                x, y = batch
                (loss, (ntv2, y_pred)), grads = grad_fn(tv, ntv, x, y)
                if mode == "synchronous" and frequency != "fit":
                    grads = jax.lax.pmean(grads, "workers")
                    ntv2 = _pmean_floats(ntv2, "workers")
                tv2, ov2 = optimizer.stateless_apply(ov, grads, tv)
                if mode != "synchronous" and frequency == "batch":
                    tv2 = _pmean_floats(tv2, "workers")
                    ntv2 = _pmean_floats(ntv2, "workers")
                mvs2 = [
                    m.stateless_update_state(mv, y, y_pred)
                    for (m, _i, _n), mv in zip(metric_objects, mvs)
                ]
                return (tv2, ntv2, ov2, mvs2), loss

            (tv, ntv, ov, mvs), losses = jax.lax.scan(
                step, (tv, ntv, ov, mvs), (xb, yb)
            )
            if mode != "synchronous" and frequency == "epoch":
                tv = _pmean_floats(tv, "workers")
                ntv = _pmean_floats(ntv, "workers")
            # merge metric states across workers (additive for Mean-types);
            # loss pmean'd so every process can read it without a gather
            mvs = jax.tree.map(lambda a: jax.lax.psum(a, "workers"), mvs)
            loss = jax.lax.pmean(jnp.mean(losses), "workers")
            return (
                _stack0(tv),
                _stack0(ntv),
                _stack0(ov),
                mvs,
                loss,
            )

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"), P("workers"), P("workers"), P(),
                      P("workers"), P("workers")),
            out_specs=(P("workers"), P("workers"), P("workers"), P(), P()),
            check_rep=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def run_epochs(
        self,
        partitions: list[tuple[np.ndarray, np.ndarray]],
        epochs: int,
        batch_size: int,
        verbose: int = 0,
        callbacks=None,
    ) -> dict:
        """Run ``epochs`` compiled epochs; returns a Keras-style history dict
        (loss + every compiled metric, like ``keras.Model.fit``) and leaves
        trained weights on the master model.

        Metric values count wrap-padded rows of ragged final batches
        (duplicated samples weigh in twice) — the same rows the loss
        already trains on; exact de-duplication would put masks in the
        train program for a sub-1% reporting delta on real shards.
        """
        if len(partitions) != self.num_workers:
            raise ValueError(
                f"got {len(partitions)} partitions for {self.num_workers} workers"
            )
        xs, ys, counts, nb = stack_worker_batches(partitions, batch_size)
        xb = self._shard_data(xs)
        yb = self._shard_data(ys)
        tv, ntv, ov = self._device_state()
        metric_objects = self._unwrapped_metrics(partitions[0][0], partitions[0][1])
        if self._epoch_fn is None:
            self._epoch_fn = self._build_epoch_fn(metric_objects)

        history: dict[str, list[float]] = {"loss": []}
        for epoch in range(epochs):
            mvs = self._zero_metric_state(metric_objects)
            tv, ntv, ov, mvs, loss = self._epoch_fn(tv, ntv, ov, mvs, xb, yb)
            epoch_loss = float(np.asarray(loss))  # replicated: direct read
            history["loss"].append(epoch_loss)
            self._history_from_metrics(history, metric_objects, mvs)
            if verbose:
                logger.info("epoch %d/%d - loss: %.4f", epoch + 1, epochs, epoch_loss)
            if callbacks:
                # sync master model before invoking, so callbacks (e.g.
                # parameter-server publication) observe live weights
                self._write_back(tv, ntv, ov)
                for cb in callbacks:
                    cb(epoch, epoch_loss)

        # 'fit' frequency (reference-parity synchronous): average once at end.
        if self.frequency == "fit":
            tv = [
                np.mean(self._gather(l), axis=0, keepdims=True).repeat(
                    self.num_workers, 0
                )
                for l in tv
            ]
            ntv = [
                np.mean(self._gather(l), axis=0, keepdims=True).repeat(
                    self.num_workers, 0
                )
                if np.issubdtype(l.dtype, np.floating)
                else self._gather(l)
                for l in ntv
            ]
        self._write_back(tv, ntv, ov)
        return history

    def run_epochs_stream(
        self,
        stream,
        epochs: int,
        verbose: int = 0,
        callbacks=None,
    ) -> dict:
        """Streamed training: like :meth:`run_epochs` but the epoch arrives
        as :class:`~elephas_tpu.data.streaming.ShardedStream` blocks that
        never all live in device memory at once.

        The same compiled epoch program runs per block (same math, same
        history), with the next block's host gather/`device_put` hidden
        under the current block's compute by async dispatch. Each block
        enters with zero metric state and leaves its psum'd (cross-worker
        additive) contribution, which accumulates across blocks — exact
        for integer and float states alike (a divide-by-W re-entry would
        silently truncate integer counters at every block boundary).
        """
        if self.frequency == "fit":
            raise ValueError(
                "frequency='fit' (train whole fit locally, average once) "
                "contradicts streaming; use 'epoch' or 'batch'"
            )
        metric_objects = self._unwrapped_metrics(
            *next(self._first_rows(stream))
        )
        if self._epoch_fn is None:
            self._epoch_fn = self._build_epoch_fn(metric_objects)
        tv, ntv, ov = self._device_state()

        # multi-host: gather only this process's workers' rows from the
        # backing store (VERDICT r2 weak #3 — full-block gathers multiply
        # storage bandwidth by the process count)
        from elephas_tpu.data.streaming import prefetch_blocks

        local_idx = (
            self._local_worker_indices() if jax.process_count() > 1 else None
        )
        history: dict[str, list[float]] = {"loss": []}
        for epoch in range(epochs):
            mvs = None  # accumulated block contributions (additive states)
            losses: list[tuple] = []
            # background reader keeps blocks ahead of the device (gathers
            # overlap compute beyond async-dispatch depth)
            for xs, ys, steps in prefetch_blocks(
                stream.blocks(worker_indices=local_idx)
            ):
                xb, yb = self._shard_local_data(xs), self._shard_local_data(ys)
                zero_mvs = self._zero_metric_state(metric_objects)
                tv, ntv, ov, block_mvs, loss = self._epoch_fn(
                    tv, ntv, ov, zero_mvs, xb, yb
                )
                mvs = (
                    block_mvs
                    if mvs is None
                    else jax.tree.map(jnp.add, mvs, block_mvs)
                )
                losses.append((loss, steps))
            total_steps = sum(s for _, s in losses)
            epoch_loss = (
                sum(float(np.asarray(l)) * s for l, s in losses) / total_steps
            )
            history["loss"].append(epoch_loss)
            self._history_from_metrics(history, metric_objects, mvs)
            if verbose:
                logger.info(
                    "epoch %d/%d - loss: %.4f (%d blocks streamed)",
                    epoch + 1, epochs, epoch_loss, len(losses),
                )
            if callbacks:
                self._write_back(tv, ntv, ov)
                for cb in callbacks:
                    cb(epoch, epoch_loss)
        self._write_back(tv, ntv, ov)
        return history

    @staticmethod
    def _first_rows(stream):
        """A (x_rows, y_rows) sample for metric building, without pulling
        a whole block."""
        yield (
            np.asarray(stream.x[0:1]),
            np.asarray(stream.y[0:1]),
        )

    def _gather(self, leaf) -> np.ndarray:
        """Full ``[W, ...]`` host value of a worker-sharded leaf — the
        shared cross-process read (:meth:`KerasIntrospection._host_read`)."""
        return self._host_read(leaf)

    # -- evaluation ----------------------------------------------------

    def _build_eval_fn(self, metric_objects, loss_keys):
        per_sample_loss = self._per_sample_loss_fn()

        def per_worker(tv, ntv, mvs, xb, yb, wb):
            # tv/ntv arrive as [1, ...] worker shards; mvs arrive whole
            # (replicated zeros) and leave whole (psum'd across workers)
            tv, ntv = _unstack0(tv), _unstack0(ntv)
            xb = xb[0]
            yb = jax.tree.map(lambda a: a[0], yb)
            wb = wb[0]
            model = self.model
            multi = len(self._output_names()) > 1

            def step(carry, batch):
                loss_sums, weight_sum, mvs = carry
                x, y, w = batch
                # return_losses: add_loss/regularizer penalties belong in
                # the reported total loss, as in keras's test_step
                y_pred, _, extra_losses = model.stateless_call(
                    tv, ntv, x, training=False, return_losses=True
                )
                extras = sum(extra_losses) if extra_losses else 0.0
                values = per_sample_loss(y, y_pred)
                loss_sums = {
                    k: loss_sums[k] + jnp.sum(values[k] * w) for k in loss_keys
                }
                # weight-scaled so the final divide leaves the penalty
                # un-normalized (it is per-model, not per-sample)
                loss_sums = dict(
                    loss_sums, loss=loss_sums["loss"] + extras * jnp.sum(w)
                )
                weight_sum = weight_sum + jnp.sum(w)
                new_mvs = []
                for (m, i, _name), mv in zip(metric_objects, mvs):
                    yi = y[i] if multi else y
                    ypi = y_pred[i] if multi else y_pred
                    new_mvs.append(
                        m.stateless_update_state(
                            mv, yi, ypi,
                            sample_weight=self._broadcast_sw(w, yi),
                        )
                    )
                return (loss_sums, weight_sum, new_mvs), None

            zeros = {k: jnp.float32(0) for k in loss_keys}
            (loss_sums, weight_sum, mvs), _ = jax.lax.scan(
                step, (zeros, jnp.float32(0), mvs), (xb, yb, wb)
            )
            # additive merge across workers (Mean-type metric states sum);
            # everything leaves replicated so any process reads it directly
            loss_sums = jax.tree.map(lambda a: jax.lax.psum(a, "workers"), loss_sums)
            weight_sum = jax.lax.psum(weight_sum, "workers")
            mvs = jax.tree.map(lambda a: jax.lax.psum(a, "workers"), mvs)
            return loss_sums, weight_sum, mvs

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"), P("workers"), P(), P("workers"),
                      P("workers"), P("workers")),
            out_specs=(P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(sharded)

    def evaluate(
        self,
        partitions: list[tuple[np.ndarray, np.ndarray]],
        batch_size: int = 32,
    ) -> dict[str, float]:
        """Distributed evaluate → ``{'loss': ..., <metric>: ...}``.

        Padding rows carry zero sample-weight, so aggregates are exact.
        Multi-output models (``y`` a list/tuple per partition, list/dict
        compiled losses) report keras-style ``<output>_loss`` and
        ``<output>_<metric>`` keys; dict insertion order is the keras
        reporting order (loss, per-output losses, metrics).
        """
        partitions = self._fit_partitions_to_mesh(partitions)
        counts = [len(x) for x, _ in partitions]
        nb = max(1, int(np.ceil(max(counts) / batch_size)))
        xs, ys, ws = [], [], []
        for x, y in partitions:
            n = len(x)
            total = nb * batch_size
            idx = np.arange(total) % n
            w = (np.arange(total) < n).astype(np.float32)
            xs.append(x[idx].reshape((nb, batch_size) + x.shape[1:]))
            ys.append(
                jax.tree.map(
                    lambda a: np.asarray(a)[idx].reshape(
                        (nb, batch_size) + np.asarray(a).shape[1:]
                    ),
                    y,
                )
            )
            ws.append(w.reshape((nb, batch_size)))
        xb = self._shard_data(np.stack(xs))
        yb = jax.tree.map(lambda *parts: self._shard_data(np.stack(parts)), *ys)
        wb = self._shard_data(np.stack(ws))

        metric_objects = self._unwrapped_metrics(partitions[0][0], partitions[0][1])
        loss_keys = self._loss_keys()
        mvs = self._zero_metric_state(metric_objects)
        tv, ntv, _ = self._device_state()

        if self._eval_fn is None:
            self._eval_fn = self._build_eval_fn(metric_objects, loss_keys)
        loss_sums, weight_sum, mvs = self._eval_fn(tv, ntv, mvs, xb, yb, wb)
        denom = float(np.asarray(weight_sum))  # replicated scalars: direct read
        results = {
            k: float(np.asarray(loss_sums[k])) / denom for k in loss_keys
        }
        tail: dict[str, list[float]] = {}
        self._history_from_metrics(tail, metric_objects, mvs)
        results.update({k: v[0] for k, v in tail.items()})
        return results

    def host_weights(self):
        """Full weights on host for parameter-server publication (the
        wire protocol is host numpy lists by contract). Current because
        run_epochs writes back before callbacks fire."""
        return self.model.get_weights()

    # -- checkpointing (runner-dispatched; SparkModel stays agnostic) ----

    def save_checkpoint(self, directory: str, epoch: int, history=None) -> None:
        """Whole-model keras archive — data-parallel replicas are
        identical post-sync, so one archive is the canonical state and
        ONLY the coordinator writes it (N gang processes writing the
        same file on shared storage would race). The TP runner's orbax
        snapshots are collective instead — every process writes its own
        shards there."""
        multiproc = jax.process_count() > 1
        try:
            if not multiproc or jax.process_index() == 0:
                from elephas_tpu.utils import checkpoint as ckpt

                ckpt.save_checkpoint(self.model, directory, epoch, history)
        finally:
            if multiproc:
                # every process calls save_checkpoint (the callback runs
                # gang-wide); barrier so nobody races ahead into a resume
                # while the coordinator's archive is mid-write. In the
                # finally block so a coordinator write failure still
                # releases the gang (and then propagates) instead of
                # deadlocking the others at this barrier.
                from elephas_tpu.parallel.distributed import sync_global_devices

                sync_global_devices(f"ckpt-save-{epoch}")

    def restore_checkpoint(self, directory: str, custom_objects=None):
        from elephas_tpu.utils import checkpoint as ckpt

        return ckpt.restore_checkpoint(self.model, directory, custom_objects)

    # -- prediction ----------------------------------------------------

    def _build_predict_fn(self):
        def per_worker(tv, ntv, xb):
            tv, ntv = _unstack0(tv), _unstack0(ntv)
            xb = xb[0]
            model = self.model

            def step(_, x):
                y_pred, _unused = model.stateless_call(tv, ntv, x, training=False)
                return None, y_pred

            _, preds = jax.lax.scan(step, None, xb)
            return preds[None]

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"), P("workers"), P("workers")),
            out_specs=P("workers"),
            check_rep=False,
        )
        return jax.jit(sharded)

    def predict(self, feature_partitions: list[np.ndarray], batch_size: int = 32) -> np.ndarray:
        feature_partitions = [p for p in feature_partitions if len(p)]
        if not feature_partitions:
            raise ValueError("predict: no input rows")
        if len(feature_partitions) > self.num_workers:
            feature_partitions = self._re_split(
                np.concatenate(feature_partitions), self.num_workers
            )
        # true row counts; mesh-filler partitions below contribute 0 rows
        counts = [len(x) for x in feature_partitions]
        while len(feature_partitions) < self.num_workers:
            feature_partitions.append(feature_partitions[-1][:1])
            counts.append(0)
        nb = max(1, int(np.ceil(max(counts) / batch_size)))
        xs = np.stack(
            [pad_to_batches(x, nb, batch_size) for x in feature_partitions]
        )
        xb = self._shard_data(xs)
        tv, ntv, _ = self._device_state()
        if self._predict_fn is None:
            self._predict_fn = self._build_predict_fn()
        preds = np.asarray(self._predict_fn(tv, ntv, xb))
        out = []
        for w, n in enumerate(counts):
            flat = preds[w].reshape((-1,) + preds.shape[3:])
            out.append(flat[:n])
        return np.concatenate(out)

    # -- partition shaping --------------------------------------------

    @staticmethod
    def _re_split(arrs, n):
        return [a for a in np.array_split(arrs, n) if len(a)]

    def _fit_partitions_to_mesh(self, partitions):
        """Coalesce/split (x, y) partitions to exactly ``num_workers``.

        ``y`` may be any pytree of row-aligned arrays (multi-output
        models evaluate with tuple/list targets).
        """
        if len(partitions) == self.num_workers:
            return partitions
        x = np.concatenate([p[0] for p in partitions])
        y = jax.tree.map(
            lambda *ps: np.concatenate([np.asarray(a) for a in ps]),
            *[p[1] for p in partitions],
        )
        xs = np.array_split(x, self.num_workers)
        offsets = np.cumsum([0] + [len(a) for a in xs])
        out = []
        for i, a in enumerate(xs):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            if len(a) == 0:
                # re-use a sample from the first shard; zero-weighted later
                a = xs[0][:1]
                b = jax.tree.map(lambda t: t[:1], y)
            else:
                b = jax.tree.map(lambda t: t[lo:hi], y)
            out.append((a, b))
        return out


# -- overlapped parameter sync (ISSUE 2 tentpole, part 3) ----------------


class OverlappedSync:
    """Background push(delta)/pull(weights) window for async/hogwild
    workers: one daemon thread owns the parameter client (a single
    connection — wire ops stay serialized), so a sync round overlaps the
    next period's compute instead of blocking it.

    Staleness bound: at most ``staleness`` rounds may be in flight;
    :meth:`submit` blocks until the oldest lands once the window is
    full. ``synchronous`` mode never routes through this class — it
    stays blocking and bit-exact.
    """

    def __init__(self, client, staleness: int = 1):
        self.client = client
        self.staleness = max(1, int(staleness))
        self._queue: queue.Queue = queue.Queue()
        self._pending: collections.deque[Future] = collections.deque()
        self.max_in_flight = 0  # high-water mark (tested staleness bound)
        # trace context is THREAD-local (ISSUE 13) and the wire ops
        # below run on this daemon thread — capture the constructing
        # thread's scope so overlapped rounds stamp (and forward) the
        # same trace id the blocking path would
        self._trace_id = telemetry.current_trace()
        self._thread = threading.Thread(
            target=self._run, name="elephas-ps-sync", daemon=True
        )
        self._thread.start()

    def _run(self):
        with telemetry.trace_scope(self._trace_id):
            while True:
                item = self._queue.get()
                if item is None:
                    return
                delta, fut = item
                try:
                    if delta is not None:
                        self.client.update_parameters(delta)
                    fut.set_result(self.client.get_parameters())
                except BaseException as e:  # surfaced at submit/drain
                    fut.set_exception(e)

    def submit(self, delta) -> Future:
        """Queue one round (push ``delta``, then pull fresh weights)."""
        while len(self._pending) >= self.staleness:
            self._pending.popleft().result()  # staleness bound: block
        fut: Future = Future()
        self._queue.put((delta, fut))
        self._pending.append(fut)
        self.max_in_flight = max(self.max_in_flight, len(self._pending))
        return fut

    def freshest(self):
        """Newest completed pull (dropping older ones), or None if every
        in-flight round is still on the wire — the caller then continues
        from its local weights, Hogwild-style."""
        newest = None
        while self._pending and self._pending[0].done():
            newest = self._pending.popleft().result()
        return newest

    def drain(self):
        """Wait for every in-flight round; returns the last pull."""
        out = None
        while self._pending:
            out = self._pending.popleft().result()
        return out

    def close(self) -> None:
        self._queue.put(None)
        self._thread.join(timeout=30)


# -- executor-side worker classes (reference API parity) ----------------


class SparkWorker:
    """Per-partition synchronous worker (``[U] elephas/worker.py::SparkWorker``).

    The compiled SPMD path above supersedes this for normal training; these
    classes are the reference-shaped escape hatch for custom per-partition
    execution (and they are what the parameter-server protocol tests drive).
    ``train(data_iterator)`` yields ``(trained_weights, history_dict)`` —
    the v3-lineage contract (SURVEY.md §2 "SparkWorker").
    """

    def __init__(
        self,
        json_model: str,
        parameters,
        train_config: dict | None = None,
        master_optimizer="rmsprop",
        master_loss="categorical_crossentropy",
        master_metrics=None,
        custom_objects: dict | None = None,
    ):
        self.json_model = json_model
        self.parameters = parameters
        self.train_config = dict(train_config or {})
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects

    def _build(self):
        import keras

        model = keras.models.model_from_json(
            self.json_model, custom_objects=self.custom_objects
        )
        model.compile(
            optimizer=self.master_optimizer,
            loss=self.master_loss,
            metrics=self.master_metrics,
        )
        if self.parameters is not None:
            model.set_weights(self.parameters)
        return model

    @staticmethod
    def _stack(data_iterator):
        xs, ys = [], []
        for x, y in data_iterator:
            xs.append(np.asarray(x))
            ys.append(np.asarray(y))
        if not xs:
            return None, None
        return np.stack(xs), np.stack(ys)

    def train(self, data_iterator):
        """Train on one partition's rows; yields (weights, history)."""
        x, y = self._stack(data_iterator)
        if x is None:
            return
        model = self._build()
        history = model.fit(
            x,
            y,
            epochs=self.train_config.get("epochs", 1),
            batch_size=self.train_config.get("batch_size", 32),
            verbose=self.train_config.get("verbose", 0),
            validation_split=self.train_config.get("validation_split", 0.0),
        )
        yield model.get_weights(), history.history


class AsynchronousSparkWorker(SparkWorker):
    """Per-partition async worker: pull → local train → push delta
    (``[U] elephas/worker.py::AsynchronousSparkWorker``).

    Speaks the real parameter-server protocol through a
    :mod:`elephas_tpu.parameter` client, so it works against a weight
    store on another host over DCN. ``frequency='epoch'`` syncs once per
    epoch, ``'batch'`` once per mini-batch.

    ISSUE 2 knobs: ``compression``/``topk`` select the binary codec's
    int8 quantization (with error-feedback residuals held by the
    client) and top-k delta sparsification; ``overlap=True`` routes
    sync rounds through :class:`OverlappedSync` so the wire rides
    under the next period's compute, trading a bounded ``staleness``
    (in sync periods) for throughput — the async/hogwild trade, never
    applied to the synchronous worker.

    ISSUE 3 (fault tolerance): each sync period runs under a
    **supervised retry** — when a period's pull/push fails even after
    the client's own reconnect retries (a PS crash/restart, a severed
    wire), the worker backs off with capped exponential delays
    (``utils.sockets.retry_call``), re-pulls fresh weights, and re-runs
    that period, up to ``ps_retries`` times before giving up; a
    transient PS outage therefore pauses training instead of killing
    it. The worker registers under ``client_id`` and heartbeats the
    server once per sync period on the existing connection, so the
    server's ``status`` op reports live membership. On protocol-2
    servers every push carries a sequence ID, making the period
    re-run's resends effectively-once (a re-run period's *recompute*
    trains that period's rows again — the documented at-least-once
    training semantic of crash recovery). With lossy compression a
    re-encoded retry folds the previous attempt's residual into the
    fresh delta — DGC's delayed-error contract, preserved across
    failures.

    ISSUE 6 (sharded PS): ``master="host:p0,host:p1,..."`` — a
    comma-separated endpoint list — routes the same pull/train/push
    loop through a :class:`~elephas_tpu.parameter.client.ShardedClient`
    (scatter/gather over per-shard servers, per-shard sequence IDs,
    one dead shard pausing only its slice). Workers may join and leave
    such a topology mid-run: registration is implicit (first heartbeat
    or sequenced update) and a departed worker's lease simply goes
    stale, so elastic data-parallel membership needs no coordinator
    round-trip.
    """

    def __init__(
        self,
        json_model: str,
        parameters=None,
        train_config: dict | None = None,
        frequency: str = "epoch",
        parameter_server_mode: str = "http",
        master: str | None = None,
        port: int = 4000,
        master_optimizer="rmsprop",
        master_loss="categorical_crossentropy",
        master_metrics=None,
        custom_objects: dict | None = None,
        compression: str = "none",
        topk: float | None = None,
        pull_compression: str | None = None,
        overlap: bool = False,
        staleness: int = 1,
        ps_retries: int = 6,
        ps_retry_max_delay: float = 5.0,
        client_id: str | None = None,
        trace_id: str | None = None,
    ):
        super().__init__(
            json_model,
            parameters,
            train_config,
            master_optimizer,
            master_loss,
            master_metrics,
            custom_objects,
        )
        if frequency not in ("epoch", "batch"):
            raise ValueError(f"frequency must be 'epoch' or 'batch', got {frequency!r}")
        self.frequency = frequency
        self.parameter_server_mode = parameter_server_mode
        self.master = master
        self.port = port
        self.compression = compression
        self.topk = topk
        self.pull_compression = pull_compression
        self.overlap = bool(overlap)
        self.staleness = max(1, int(staleness))
        self.ps_retries = max(0, int(ps_retries))
        self.ps_retry_max_delay = float(ps_retry_max_delay)
        self.client_id = client_id
        # cross-process trace context (ISSUE 13): when set, train()
        # runs under this trace id — its sync spans, retries, and PS
        # round-trips all stamp it, and the clients forward it over
        # the wire so server-side applies join the same trace. When
        # None, train() inherits the caller's ambient scope (the chaos
        # harness / SparkModel.fit shape).
        self.trace_id = trace_id
        # telemetry (ISSUE 5): the supervised retry loop and sync
        # cadence become observable — a rising retry rate is the
        # earliest signal of a struggling PS, visible on the same
        # scrape as the server's own counters
        reg = telemetry.registry()
        wid = telemetry.instance_label()
        self.telemetry_label = wid
        self._tracer = telemetry.tracer()
        self._m_sync_periods = reg.counter(
            "elephas_worker_sync_periods_total",
            "Completed pull-train-push sync periods",
            labels=("worker",),
        ).labels(worker=wid)
        self._m_retries = reg.counter(
            "elephas_worker_ps_retries_total",
            "Supervised re-runs of a sync period after a PS failure",
            labels=("worker",),
        ).labels(worker=wid)

    def release_telemetry(self) -> None:
        """Retire this worker's labeled series from the process
        registry. Explicit-only (see ``Registry.remove_series``):
        post-fit scrapes showing what the partitions did are a
        supported shape, so retirement is the host's call."""
        telemetry.remove_series(worker=self.telemetry_label)

    def _client(self, model=None):
        from elephas_tpu.parameter.client import HttpClient, SocketClient

        if self.master and "," in str(self.master):
            # sharded topology (ISSUE 6): a comma-separated endpoint
            # list selects the scatter/gather client — the worker
            # derives the SAME deterministic shard map the server group
            # derived from the same weight template
            from elephas_tpu.parameter.client import ShardedClient
            from elephas_tpu.parameter.sharding import (
                ShardMap,
                shard_endpoints,
            )

            if self.parameter_server_mode not in ("http", "socket"):
                raise ValueError(
                    f"sharded endpoint lists need parameter_server_mode="
                    f"'http' or 'socket', got "
                    f"{self.parameter_server_mode!r}"
                )
            if model is None:
                raise ValueError(
                    "sharded endpoints need the built model to derive "
                    "the shard map from its weight template"
                )
            endpoints = shard_endpoints(self.master)
            return ShardedClient(
                endpoints,
                ShardMap.from_weights(model.get_weights(), len(endpoints)),
                transport=self.parameter_server_mode,
                client_id=self.client_id,
                compression=self.compression, topk=self.topk,
                pull_compression=self.pull_compression,
                retries=max(3, self.ps_retries) if self.overlap else 3,
            )
        if self.parameter_server_mode == "native":
            if (
                self.compression != "none"
                or self.topk is not None
                or self.pull_compression not in (None, "none")
            ):
                raise ValueError(
                    "the native parameter server speaks raw float32 "
                    "frames — compression/topk need "
                    "parameter_server_mode='http' or 'socket'"
                )
            from elephas_tpu.parameter.native import NativeClient, _Flattener

            host, _, p = (self.master or "127.0.0.1").partition(":")
            port = int(p) if p else self.port
            return NativeClient(host, port, _Flattener(model.get_weights()))
        cls = {"http": HttpClient, "socket": SocketClient}.get(
            self.parameter_server_mode
        )
        if cls is None:
            raise ValueError(
                f"parameter_server_mode must be 'http', 'socket' or "
                f"'native', got {self.parameter_server_mode!r}"
            )
        # overlap rounds ride a background thread where the supervised
        # period re-run below cannot reach them — give the client itself
        # the longer retry horizon there
        retries = max(3, self.ps_retries) if self.overlap else 3
        return cls(
            self.master, self.port,
            compression=self.compression, topk=self.topk,
            pull_compression=self.pull_compression,
            retries=retries, client_id=self.client_id,
        )

    def _periods(self, x, y, epochs: int, batch_size: int):
        """The sync-period stream: whole epochs or mini-batches."""
        for _ in range(epochs):
            if self.frequency == "epoch":
                yield x, y
            else:
                for start in range(0, len(x), batch_size):
                    yield x[start : start + batch_size], y[start : start + batch_size]

    def _fit_period(self, model, xp, yp, batch_size: int) -> None:
        if self.frequency == "epoch":
            model.fit(xp, yp, epochs=1, batch_size=batch_size, verbose=0)
        else:
            model.train_on_batch(xp, yp)

    def _heartbeat(self, client) -> None:
        """Best-effort lease refresh once per sync period (liveness is
        advisory; the period's own ops carry the hard failure path)."""
        beat = getattr(client, "heartbeat", None)
        if beat is None:
            return
        try:
            beat()
        except (ConnectionError, TimeoutError, OSError) as e:
            logger.debug("heartbeat failed (non-fatal): %r", e)

    def _supervised(self, fn):
        """One sync period under the ISSUE 3 supervision contract:
        capped-backoff re-runs survive a PS outage that outlasts the
        client's own reconnect retries; the final failure propagates
        so the driver's failure budget can count this worker. Each
        re-run counts in ``elephas_worker_ps_retries_total`` and lands
        as a trace event (ISSUE 5) so outage windows line up with the
        chaos timeline."""

        def on_retry(attempt, exc):
            self._m_retries.inc()
            self._tracer.emit(
                "worker.retry", worker=self.telemetry_label,
                attempt=attempt, error=repr(exc),
            )

        return sockets.retry_call(
            fn,
            retries=self.ps_retries,
            base_delay=0.25,
            max_delay=self.ps_retry_max_delay,
            on_retry=on_retry,
        )

    def train(self, data_iterator):
        from elephas_tpu.utils.functional_utils import subtract_params

        x, y = self._stack(data_iterator)
        if x is None:
            return
        # trace_scope(None) is a passthrough: without an explicit
        # trace_id this worker inherits whatever scope the caller set
        with telemetry.trace_scope(self.trace_id):
            yield from self._train_scoped(x, y, subtract_params)

    def _train_scoped(self, x, y, subtract_params):
        model = self._build()
        client = self._client(model)
        epochs = self.train_config.get("epochs", 1)
        batch_size = self.train_config.get("batch_size", 32)
        try:
            if self.overlap:
                self._train_overlapped(
                    model, client, x, y, epochs, batch_size
                )
            else:
                for xp, yp in self._periods(x, y, epochs, batch_size):

                    def sync_period(xp=xp, yp=yp):
                        # resume-from-last-PS-pull: every (re-)run of a
                        # period starts from fresh server weights, so a
                        # re-run after an outage trains on the
                        # post-recovery state, not a stale snapshot
                        self._heartbeat(client)
                        before = client.get_parameters()
                        model.set_weights(before)
                        self._fit_period(model, xp, yp, batch_size)
                        # server applies weights += delta, so the delta
                        # must be the descent step (after − before)
                        client.update_parameters(
                            subtract_params(model.get_weights(), before)
                        )

                    with self._tracer.span(
                        "worker.sync_period",
                        worker=self.telemetry_label,
                    ):
                        self._supervised(sync_period)
                    self._m_sync_periods.inc()
                # confirmed delivery: every pipelined push is acked (or
                # sequence-deduplicated-resent) before this partition
                # reports done — without this, a connection dying on
                # the run's FINAL pushes would lose them silently
                flush = getattr(client, "flush", None)
                if flush is not None:
                    self._supervised(flush)
        finally:
            if hasattr(client, "close"):
                client.close()
        yield model.get_weights(), {}

    def _train_overlapped(self, model, client, x, y, epochs, batch_size):
        """Double-buffered loop: period ``i``'s compute overlaps round
        ``i-1``'s push+pull; adopted weights are stale by at most
        ``staleness`` periods (else the worker continues from its own
        local weights, Hogwild-style)."""
        from elephas_tpu.utils.functional_utils import subtract_params

        sync = OverlappedSync(client, self.staleness)
        try:
            before = client.get_parameters()  # initial pull: blocking
            model.set_weights(before)
            for xp, yp in self._periods(x, y, epochs, batch_size):
                self._fit_period(model, xp, yp, batch_size)
                after = model.get_weights()
                sync.submit(subtract_params(after, before))
                self._m_sync_periods.inc()
                fresh = sync.freshest()
                if fresh is not None:
                    before = fresh
                    model.set_weights(fresh)
                else:
                    # round still on the wire: continue from local
                    # weights (Hogwild-style), no extra copies
                    before = after
            final = sync.drain()  # every push acked before we report
            if final is not None:
                model.set_weights(final)
        finally:
            sync.close()
