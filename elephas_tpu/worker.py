"""Compiled distributed training programs — the SparkWorker equivalent.

Reference surface: ``[U] elephas/worker.py`` — ``SparkWorker`` (synchronous)
and ``AsynchronousSparkWorker`` rebuild the Keras model inside each Spark
executor, run local ``model.fit`` over their RDD partition, and exchange
weights either by driver-side averaging or through a pickle-over-HTTP/TCP
parameter server (SURVEY.md §3.1/3.2).

TPU-first redesign: there are no worker processes. A whole training epoch
for *all* workers is one XLA program — ``jax.jit(shard_map(...))`` over a
1-D ``('workers',)`` mesh:

- each worker's parameters/optimizer state live as one shard of a stacked
  ``[W, ...]`` array (its leading-axis slice), so "per-worker model
  replicas" are just a sharded pytree;
- the per-batch loop is ``lax.scan`` — no Python, no dispatch, no pickle;
- weight synchronization is ``lax.pmean`` compiled into the program,
  riding ICI/DCN instead of the reference's Flask/socket round-trips.

Mode semantics (see SURVEY.md §2a):

- ``synchronous``: gradients are ``pmean``-ed across workers every step
  (replicas stay bit-identical — classic SPMD data parallelism; the
  north-star path). The reference's coarser "train whole fit locally,
  average once" behavior is available as ``frequency='fit'``.
- ``asynchronous``: workers take independent local steps; weights (and
  float non-trainable state) are ``pmean``-averaged at each ``frequency``
  boundary (``'batch'`` or ``'epoch'``) — local-SGD with a staleness bound
  of one period, the honest SPMD mapping of the reference's
  parameter-server staleness.
- ``hogwild``: same schedule as ``asynchronous``. The reference's only
  difference is eliding a server-side lock (a *race*, not an algorithm);
  on gang-scheduled TPUs there is no lock to elide, so the two modes are
  computationally identical here. The semantic difference is documented
  rather than simulated.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
    return jax.shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep
    )

logger = logging.getLogger(__name__)

MODES = ("synchronous", "asynchronous", "hogwild")
FREQUENCIES = ("epoch", "batch", "fit")


def _pmean_floats(tree, axis_name: str):
    """pmean float leaves; pass integer leaves (counters, seeds) through."""
    return jax.tree.map(
        lambda a: jax.lax.pmean(a, axis_name)
        if jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        tree,
    )


def _unstack0(tree):
    return jax.tree.map(lambda a: a[0], tree)


def _stack0(tree):
    return jax.tree.map(lambda a: a[None], tree)


def pad_to_batches(x: np.ndarray, num_batches: int, batch_size: int) -> np.ndarray:
    """Wrap-pad rows so ``x`` reshapes to ``[num_batches, batch_size, ...]``.

    Wrap-around duplication (rather than zero-pad + masking) keeps the
    training program mask-free; duplicated samples slightly overweight a
    few rows in the last partial batch, matching the spirit of the
    reference's per-worker ``model.fit`` which also sees a ragged final
    batch.
    """
    n = len(x)
    total = num_batches * batch_size
    if n == 0:
        raise ValueError("cannot pad an empty partition")
    idx = np.arange(total) % n
    return x[idx].reshape((num_batches, batch_size) + x.shape[1:])


def stack_worker_batches(
    partitions: list[tuple[np.ndarray, np.ndarray]],
    batch_size: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Partition arrays → ``x[W, nb, B, ...]``, ``y[W, nb, B, ...]``.

    Also returns per-worker true sample counts and the common batch count
    (the max over workers — shorter partitions wrap).
    """
    counts = np.array([len(x) for x, _ in partitions])
    nb = max(1, int(np.ceil(counts.max() / batch_size)))
    xs = np.stack([pad_to_batches(x, nb, batch_size) for x, _ in partitions])
    ys = np.stack([pad_to_batches(y, nb, batch_size) for _, y in partitions])
    return xs, ys, counts, nb


class MeshRunner:
    """Owns the compiled train/eval/predict programs for one Keras model.

    The model must be compiled (optimizer/loss/metrics) and built. All
    programs are cached per (static-shape) signature, so repeated ``fit``
    epochs reuse one executable.
    """

    def __init__(self, model, mode: str, frequency: str, mesh: Mesh):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if frequency not in FREQUENCIES:
            raise ValueError(
                f"frequency must be one of {FREQUENCIES}, got {frequency!r}"
            )
        self.model = model
        self.mode = mode
        self.frequency = frequency
        self.mesh = mesh
        self.num_workers = mesh.devices.size
        self._epoch_fn = None
        self._eval_fn = None
        self._predict_fn = None
        model.optimizer.build(model.trainable_variables)

    # -- state plumbing ------------------------------------------------

    def _host_state(self):
        tv = [np.asarray(v.value) for v in self.model.trainable_variables]
        ntv = [np.asarray(v.value) for v in self.model.non_trainable_variables]
        ov = [np.asarray(v.value) for v in self.model.optimizer.variables]
        return tv, ntv, ov

    def _device_state(self, stacked: bool = True):
        """Current model state, replicated to ``[W, ...]`` worker shards."""
        W = self.num_workers
        sharding = NamedSharding(self.mesh, P("workers"))
        tv, ntv, ov = self._host_state()

        def rep(leaf):
            return jax.device_put(
                np.broadcast_to(leaf[None], (W,) + leaf.shape), sharding
            )

        return (
            [rep(l) for l in tv],
            [rep(l) for l in ntv],
            [rep(l) for l in ov],
        )

    def _shard_data(self, arr: np.ndarray):
        return jax.device_put(arr, NamedSharding(self.mesh, P("workers")))

    def _write_back(self, tv, ntv, ov=None):
        """Worker-0 slice → model variables (all replicas agree post-sync)."""
        for var, leaf in zip(self.model.trainable_variables, tv):
            var.assign(np.asarray(leaf[0]))
        for var, leaf in zip(self.model.non_trainable_variables, ntv):
            var.assign(np.asarray(leaf[0]))
        if ov is not None:
            for var, leaf in zip(self.model.optimizer.variables, ov):
                var.assign(np.asarray(leaf[0]))

    # -- loss helpers --------------------------------------------------

    def _loss_and_updates(self, tv, ntv, x, y):
        y_pred, ntv2 = self.model.stateless_call(tv, ntv, x, training=True)
        loss = self.model.compute_loss(x=x, y=y, y_pred=y_pred)
        return loss, ntv2

    def _per_sample_loss_fn(self):
        import keras

        loss = self.model.loss
        if isinstance(loss, str):
            fn = keras.losses.get(loss)  # plain function: per-sample values
        elif isinstance(loss, keras.losses.Loss):
            fn = loss.call  # unreduced
        elif callable(loss):
            fn = loss
        else:
            raise ValueError(
                f"unsupported loss spec {loss!r} (multi-output losses not yet "
                "supported by the distributed evaluator)"
            )

        def aligned(y, y_pred):
            # keras Loss.__call__ squeezes/expands rank-mismatched targets
            # (e.g. binary y [B] vs y_pred [B,1]); raw loss fns don't
            y = jnp.asarray(y)
            if y.ndim == y_pred.ndim - 1 and y_pred.shape[-1] == 1:
                y = y[..., None]
            return fn(y, y_pred)

        return aligned

    def _unwrapped_metrics(self, x_sample, y_sample):
        """Compiled metric objects, built and with CompileMetrics expanded.

        CompileMetrics mishandles ``sample_weight`` in its count update
        (observed keras 3.13), so the underlying metrics are used directly
        for exact padded-batch aggregation. CompileMetrics (and its inner
        metrics) build lazily — force variable creation with one tiny
        host-side update, then reset.
        """
        yp = np.asarray(self.model(x_sample[:1], training=False))
        out = []
        for m in self.model.metrics:
            if m.name == "loss":
                continue
            if not getattr(m, "metrics", None) and not m.variables:
                m.update_state(y_sample[:1], yp)
                m.reset_state()
            inner = getattr(m, "metrics", None)
            if inner:
                out.extend(inner)
            else:
                out.append(m)
        for m in out:
            if not m.variables:
                m.update_state(y_sample[:1], yp)
                m.reset_state()
        return out

    # -- training ------------------------------------------------------

    def _build_epoch_fn(self):
        mode, frequency = self.mode, self.frequency
        grad_fn = jax.value_and_grad(self._loss_and_updates, has_aux=True)
        optimizer = self.model.optimizer

        def per_worker(tv, ntv, ov, xb, yb):
            # leaves arrive as the worker's [1, ...] shard
            tv, ntv, ov = _unstack0(tv), _unstack0(ntv), _unstack0(ov)
            xb, yb = xb[0], yb[0]

            def step(carry, batch):
                tv, ntv, ov = carry
                x, y = batch
                (loss, ntv2), grads = grad_fn(tv, ntv, x, y)
                if mode == "synchronous" and frequency != "fit":
                    grads = jax.lax.pmean(grads, "workers")
                    ntv2 = _pmean_floats(ntv2, "workers")
                tv2, ov2 = optimizer.stateless_apply(ov, grads, tv)
                if mode != "synchronous" and frequency == "batch":
                    tv2 = _pmean_floats(tv2, "workers")
                    ntv2 = _pmean_floats(ntv2, "workers")
                return (tv2, ntv2, ov2), loss

            (tv, ntv, ov), losses = jax.lax.scan(step, (tv, ntv, ov), (xb, yb))
            if mode != "synchronous" and frequency == "epoch":
                tv = _pmean_floats(tv, "workers")
                ntv = _pmean_floats(ntv, "workers")
            loss = jnp.mean(losses)
            return (
                _stack0(tv),
                _stack0(ntv),
                _stack0(ov),
                loss[None],
            )

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"), P("workers"), P("workers"), P("workers"), P("workers")),
            out_specs=(P("workers"), P("workers"), P("workers"), P("workers")),
            check_rep=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    def run_epochs(
        self,
        partitions: list[tuple[np.ndarray, np.ndarray]],
        epochs: int,
        batch_size: int,
        verbose: int = 0,
        callbacks=None,
    ) -> dict:
        """Run ``epochs`` compiled epochs; returns a Keras-style history dict
        and leaves trained weights on the master model."""
        if len(partitions) != self.num_workers:
            raise ValueError(
                f"got {len(partitions)} partitions for {self.num_workers} workers"
            )
        xs, ys, counts, nb = stack_worker_batches(partitions, batch_size)
        xb = self._shard_data(xs)
        yb = self._shard_data(ys)
        tv, ntv, ov = self._device_state()
        if self._epoch_fn is None:
            self._epoch_fn = self._build_epoch_fn()

        history: dict[str, list[float]] = {"loss": []}
        for epoch in range(epochs):
            tv, ntv, ov, losses = self._epoch_fn(tv, ntv, ov, xb, yb)
            epoch_loss = float(np.mean(np.asarray(losses)))
            history["loss"].append(epoch_loss)
            if verbose:
                logger.info("epoch %d/%d - loss: %.4f", epoch + 1, epochs, epoch_loss)
            if callbacks:
                # sync master model before invoking, so callbacks (e.g.
                # parameter-server publication) observe live weights
                self._write_back(tv, ntv, ov)
                for cb in callbacks:
                    cb(epoch, epoch_loss)

        # 'fit' frequency (reference-parity synchronous): average once at end.
        if self.frequency == "fit":
            tv = [np.mean(np.asarray(l), axis=0, keepdims=True).repeat(self.num_workers, 0) for l in tv]
            ntv = [
                np.mean(np.asarray(l), axis=0, keepdims=True).repeat(self.num_workers, 0)
                if np.issubdtype(np.asarray(l).dtype, np.floating)
                else np.asarray(l)
                for l in ntv
            ]
        self._write_back(tv, ntv, ov)
        return history

    # -- evaluation ----------------------------------------------------

    def _build_eval_fn(self, metric_objects):
        per_sample_loss = self._per_sample_loss_fn()

        def per_worker(tv, ntv, mvs, xb, yb, wb):
            tv, ntv = _unstack0(tv), _unstack0(ntv)
            mvs = _unstack0(mvs)
            xb, yb, wb = xb[0], yb[0], wb[0]
            model = self.model

            def step(carry, batch):
                loss_sum, weight_sum, mvs = carry
                x, y, w = batch
                y_pred, _ = model.stateless_call(tv, ntv, x, training=False)
                values = per_sample_loss(y, y_pred)
                loss_sum = loss_sum + jnp.sum(values * w)
                weight_sum = weight_sum + jnp.sum(w)
                new_mvs = []
                for m, mv in zip(metric_objects, mvs):
                    new_mvs.append(
                        m.stateless_update_state(mv, y, y_pred, sample_weight=w)
                    )
                return (loss_sum, weight_sum, new_mvs), None

            init_mvs = mvs
            (loss_sum, weight_sum, mvs), _ = jax.lax.scan(
                step, (jnp.float32(0), jnp.float32(0), init_mvs), (xb, yb, wb)
            )
            # additive merge across workers (Mean-type metric states sum)
            loss_sum = jax.lax.psum(loss_sum, "workers")
            weight_sum = jax.lax.psum(weight_sum, "workers")
            mvs = jax.tree.map(lambda a: jax.lax.psum(a, "workers"), mvs)
            return loss_sum[None], weight_sum[None], _stack0(mvs)

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"),) * 6,
            out_specs=(P("workers"), P("workers"), P("workers")),
            check_rep=False,
        )
        return jax.jit(sharded)

    def evaluate(
        self,
        partitions: list[tuple[np.ndarray, np.ndarray]],
        batch_size: int = 32,
    ) -> dict[str, float]:
        """Distributed evaluate → ``{'loss': ..., <metric>: ...}``.

        Padding rows carry zero sample-weight, so aggregates are exact.
        """
        partitions = self._fit_partitions_to_mesh(partitions)
        counts = [len(x) for x, _ in partitions]
        nb = max(1, int(np.ceil(max(counts) / batch_size)))
        xs, ys, ws = [], [], []
        for x, y in partitions:
            n = len(x)
            total = nb * batch_size
            idx = np.arange(total) % n
            w = (np.arange(total) < n).astype(np.float32)
            xs.append(x[idx].reshape((nb, batch_size) + x.shape[1:]))
            ys.append(y[idx].reshape((nb, batch_size) + y.shape[1:]))
            ws.append(w.reshape((nb, batch_size)))
        xb = self._shard_data(np.stack(xs))
        yb = self._shard_data(np.stack(ys))
        wb = self._shard_data(np.stack(ws))

        metric_objects = self._unwrapped_metrics(partitions[0][0], partitions[0][1])
        mvs = []
        W = self.num_workers
        sharding = NamedSharding(self.mesh, P("workers"))
        for m in metric_objects:
            zeros = [np.zeros(v.shape, v.dtype) for v in m.variables]
            mvs.append(
                [
                    jax.device_put(np.broadcast_to(z[None], (W,) + z.shape), sharding)
                    for z in zeros
                ]
            )
        tv, ntv, _ = self._device_state()

        if self._eval_fn is None:
            self._eval_fn = self._build_eval_fn(metric_objects)
        loss_sum, weight_sum, mvs = self._eval_fn(tv, ntv, mvs, xb, yb, wb)
        results = {
            "loss": float(np.asarray(loss_sum)[0] / np.asarray(weight_sum)[0])
        }
        for m, mv in zip(metric_objects, mvs):
            res = m.stateless_result(_unstack0(mv))
            if isinstance(res, dict):
                for k, v in res.items():
                    results[k] = float(np.asarray(v))
            else:
                results[m.name] = float(np.asarray(res))
        return results

    # -- prediction ----------------------------------------------------

    def _build_predict_fn(self):
        def per_worker(tv, ntv, xb):
            tv, ntv = _unstack0(tv), _unstack0(ntv)
            xb = xb[0]
            model = self.model

            def step(_, x):
                y_pred, _unused = model.stateless_call(tv, ntv, x, training=False)
                return None, y_pred

            _, preds = jax.lax.scan(step, None, xb)
            return preds[None]

        sharded = shard_map(
            per_worker,
            mesh=self.mesh,
            in_specs=(P("workers"), P("workers"), P("workers")),
            out_specs=P("workers"),
            check_rep=False,
        )
        return jax.jit(sharded)

    def predict(self, feature_partitions: list[np.ndarray], batch_size: int = 32) -> np.ndarray:
        feature_partitions = [p for p in feature_partitions if len(p)]
        if not feature_partitions:
            raise ValueError("predict: no input rows")
        if len(feature_partitions) > self.num_workers:
            feature_partitions = self._re_split(
                np.concatenate(feature_partitions), self.num_workers
            )
        # true row counts; mesh-filler partitions below contribute 0 rows
        counts = [len(x) for x in feature_partitions]
        while len(feature_partitions) < self.num_workers:
            feature_partitions.append(feature_partitions[-1][:1])
            counts.append(0)
        nb = max(1, int(np.ceil(max(counts) / batch_size)))
        xs = np.stack(
            [pad_to_batches(x, nb, batch_size) for x in feature_partitions]
        )
        xb = self._shard_data(xs)
        tv, ntv, _ = self._device_state()
        if self._predict_fn is None:
            self._predict_fn = self._build_predict_fn()
        preds = np.asarray(self._predict_fn(tv, ntv, xb))
        out = []
        for w, n in enumerate(counts):
            flat = preds[w].reshape((-1,) + preds.shape[3:])
            out.append(flat[:n])
        return np.concatenate(out)

    # -- partition shaping --------------------------------------------

    @staticmethod
    def _re_split(arrs, n):
        return [a for a in np.array_split(arrs, n) if len(a)]

    def _fit_partitions_to_mesh(self, partitions):
        """Coalesce/split (x, y) partitions to exactly ``num_workers``."""
        if len(partitions) == self.num_workers:
            return partitions
        x = np.concatenate([p[0] for p in partitions])
        y = np.concatenate([p[1] for p in partitions])
        xs = np.array_split(x, self.num_workers)
        ys = np.array_split(y, self.num_workers)
        out = []
        for a, b in zip(xs, ys):
            if len(a) == 0:
                # re-use a sample from the first shard; zero-weighted later
                a, b = xs[0][:1], ys[0][:1]
            out.append((a, b))
        return out


# -- executor-side worker classes (reference API parity) ----------------


class SparkWorker:
    """Per-partition synchronous worker (``[U] elephas/worker.py::SparkWorker``).

    The compiled SPMD path above supersedes this for normal training; these
    classes are the reference-shaped escape hatch for custom per-partition
    execution (and they are what the parameter-server protocol tests drive).
    ``train(data_iterator)`` yields ``(trained_weights, history_dict)`` —
    the v3-lineage contract (SURVEY.md §2 "SparkWorker").
    """

    def __init__(
        self,
        json_model: str,
        parameters,
        train_config: dict | None = None,
        master_optimizer="rmsprop",
        master_loss="categorical_crossentropy",
        master_metrics=None,
        custom_objects: dict | None = None,
    ):
        self.json_model = json_model
        self.parameters = parameters
        self.train_config = dict(train_config or {})
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects

    def _build(self):
        import keras

        model = keras.models.model_from_json(
            self.json_model, custom_objects=self.custom_objects
        )
        model.compile(
            optimizer=self.master_optimizer,
            loss=self.master_loss,
            metrics=self.master_metrics,
        )
        if self.parameters is not None:
            model.set_weights(self.parameters)
        return model

    @staticmethod
    def _stack(data_iterator):
        xs, ys = [], []
        for x, y in data_iterator:
            xs.append(np.asarray(x))
            ys.append(np.asarray(y))
        if not xs:
            return None, None
        return np.stack(xs), np.stack(ys)

    def train(self, data_iterator):
        """Train on one partition's rows; yields (weights, history)."""
        x, y = self._stack(data_iterator)
        if x is None:
            return
        model = self._build()
        history = model.fit(
            x,
            y,
            epochs=self.train_config.get("epochs", 1),
            batch_size=self.train_config.get("batch_size", 32),
            verbose=self.train_config.get("verbose", 0),
            validation_split=self.train_config.get("validation_split", 0.0),
        )
        yield model.get_weights(), history.history


class AsynchronousSparkWorker(SparkWorker):
    """Per-partition async worker: pull → local train → push delta
    (``[U] elephas/worker.py::AsynchronousSparkWorker``).

    Speaks the real parameter-server protocol through a
    :mod:`elephas_tpu.parameter` client, so it works against a weight
    store on another host over DCN. ``frequency='epoch'`` syncs once per
    epoch, ``'batch'`` once per mini-batch.
    """

    def __init__(
        self,
        json_model: str,
        parameters=None,
        train_config: dict | None = None,
        frequency: str = "epoch",
        parameter_server_mode: str = "http",
        master: str | None = None,
        port: int = 4000,
        master_optimizer="rmsprop",
        master_loss="categorical_crossentropy",
        master_metrics=None,
        custom_objects: dict | None = None,
    ):
        super().__init__(
            json_model,
            parameters,
            train_config,
            master_optimizer,
            master_loss,
            master_metrics,
            custom_objects,
        )
        if frequency not in ("epoch", "batch"):
            raise ValueError(f"frequency must be 'epoch' or 'batch', got {frequency!r}")
        self.frequency = frequency
        self.parameter_server_mode = parameter_server_mode
        self.master = master
        self.port = port

    def _client(self, model=None):
        from elephas_tpu.parameter.client import HttpClient, SocketClient

        if self.parameter_server_mode == "native":
            from elephas_tpu.parameter.native import NativeClient, _Flattener

            host, _, p = (self.master or "127.0.0.1").partition(":")
            port = int(p) if p else self.port
            return NativeClient(host, port, _Flattener(model.get_weights()))
        cls = {"http": HttpClient, "socket": SocketClient}.get(
            self.parameter_server_mode
        )
        if cls is None:
            raise ValueError(
                f"parameter_server_mode must be 'http', 'socket' or "
                f"'native', got {self.parameter_server_mode!r}"
            )
        return cls(self.master, self.port)

    def train(self, data_iterator):
        from elephas_tpu.utils.functional_utils import subtract_params

        x, y = self._stack(data_iterator)
        if x is None:
            return
        model = self._build()
        client = self._client(model)
        epochs = self.train_config.get("epochs", 1)
        batch_size = self.train_config.get("batch_size", 32)
        try:
            for _ in range(epochs):
                if self.frequency == "epoch":
                    before = client.get_parameters()
                    model.set_weights(before)
                    model.fit(x, y, epochs=1, batch_size=batch_size, verbose=0)
                    # server applies weights += delta, so the delta must be
                    # the descent step (after − before)
                    client.update_parameters(
                        subtract_params(model.get_weights(), before)
                    )
                else:  # per-batch
                    for start in range(0, len(x), batch_size):
                        xb = x[start : start + batch_size]
                        yb = y[start : start + batch_size]
                        before = client.get_parameters()
                        model.set_weights(before)
                        model.train_on_batch(xb, yb)
                        client.update_parameters(
                            subtract_params(model.get_weights(), before)
                        )
        finally:
            if hasattr(client, "close"):
                client.close()
        yield model.get_weights(), {}
