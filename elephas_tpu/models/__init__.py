"""Model zoo covering the reference's example/benchmark configurations.

The reference ships *examples*, not a model zoo — users hand
``SparkModel`` an arbitrary compiled Keras model, and the example scripts
(``[U] elephas examples/``: MNIST MLP, CIFAR-style convnets, IMDB LSTM)
build those models inline. Here the same architectures are first-class
builders so the benchmark suite (BASELINE.md configs 1–5) and the examples
share one definition. All builders return *compiled* Keras-3 (jax backend)
models ready to wrap in ``SparkModel``.
"""

from elephas_tpu.models.mlp import mnist_mlp
from elephas_tpu.models.convnet import cifar10_cnn
from elephas_tpu.models.lstm import imdb_lstm
from elephas_tpu.models.resnet import resnet50, resnet
from elephas_tpu.models.transformer import (
    generate,
    transformer_classifier,
    transformer_lm,
)
from elephas_tpu.models.switch import (
    switch_transformer_classifier,
    switch_transformer_lm,
)

__all__ = [
    "mnist_mlp",
    "cifar10_cnn",
    "imdb_lstm",
    "resnet50",
    "resnet",
    "transformer_classifier",
    "transformer_lm",
    "generate",
    "switch_transformer_classifier",
    "switch_transformer_lm",
    "MoeFFN",
    "FlashMHA",
    "FusedLayerNorm",
]


def __getattr__(name):
    # lazily resolve layer classes that require keras at definition time
    if name == "FlashMHA":
        from elephas_tpu.models.transformer import _flash_mha_layer

        return _flash_mha_layer()
    if name == "FusedLayerNorm":
        from elephas_tpu.models.transformer import _fused_ln_layer

        return _fused_ln_layer()
    if name == "MoeFFN":
        from elephas_tpu.models.switch import MoeFFN

        return MoeFFN
    raise AttributeError(name)
