"""Transformer model family — flash-attention-backed, TPU-first.

The reference's deepest sequence model is a single LSTM (its IMDB
example); transformers are the modern load-bearing family, so this module
provides them as first-class zoo members:

- :class:`FlashMHA` — Keras multi-head attention layer whose core runs
  the Pallas flash kernel (:mod:`elephas_tpu.ops.flash_attention`);
  O(S) memory, MXU-tiled.
- :func:`transformer_classifier` — encoder stack + pooled head (the
  IMDB-class task at transformer quality).
- :func:`transformer_lm` — causal decoder-only language model.

Both builders return compiled models that drop straight into
``SparkModel`` for data-parallel training; with
``elephas_tpu.ops.ring_attention`` the same attention math extends to
sequence-parallel long-context training (SURVEY.md §5 lists all of this
as absent upstream — TPU-native extension, not a port).
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np


def _keras():
    import keras

    return keras


@contextlib.contextmanager
def _dtype_policy_scope(keras, policy: str | None):
    """Temporarily set Keras's global dtype policy while building layers
    (restored even on build failure — the global must not leak)."""
    prev = keras.config.dtype_policy()
    if policy is not None:
        keras.config.set_dtype_policy(policy)
    try:
        yield
    finally:
        keras.config.set_dtype_policy(prev)


_FLASH_MHA_CLS = None


def _flash_mha_layer():
    """The FlashMHA layer class, created lazily (keras must be imported
    under the jax backend first) and registered with Keras's serializer
    so save/load/checkpoint-resume need no custom_objects."""
    global _FLASH_MHA_CLS
    if _FLASH_MHA_CLS is not None:
        return _FLASH_MHA_CLS
    import keras

    @keras.saving.register_keras_serializable(package="elephas_tpu")
    class FlashMHA(keras.layers.Layer):
        """Multi-head self-attention over the Pallas flash kernel.

        Equivalent math to ``keras.layers.MultiHeadAttention`` (fused
        qkv projection, per-head scaled dot-product, output projection)
        but the attention core never materializes the [S, S] matrix.
        """

        def __init__(self, num_heads: int, head_dim: int, causal: bool = False,
                     rope: bool = False, **kwargs):
            super().__init__(**kwargs)
            self.num_heads = num_heads
            self.head_dim = head_dim
            self.causal = causal
            self.rope = rope
            if rope and head_dim % 2:
                raise ValueError(
                    f"rope needs an even head_dim, got {head_dim}"
                )

        def build(self, input_shape):
            d_model = int(input_shape[-1])
            self.qkv = keras.layers.Dense(
                3 * self.num_heads * self.head_dim, use_bias=False, name="qkv"
            )
            self.proj = keras.layers.Dense(d_model, name="proj")
            self.qkv.build(input_shape)
            self.proj.build(
                tuple(input_shape[:-1]) + (self.num_heads * self.head_dim,)
            )
            super().build(input_shape)

        def call(self, x):
            import jax.numpy as jnp

            from elephas_tpu.parallel.sequence import (
                active_sequence_scope, ring_mha,
            )

            from elephas_tpu.ops.flash_attention import (
                flash_attention,
                flash_attention_qkv,
            )

            B = jnp.shape(x)[0]
            S = x.shape[1]
            H, D = self.num_heads, self.head_dim
            qkv = self.qkv(x)  # [B, S, 3*H*D]
            qkv = jnp.reshape(qkv, (B, S, 3, H, D))
            scope = active_sequence_scope()
            if scope is not None or self.rope:
                # transposed path: the SP ring wants separate q/k/v, and
                # rope must rotate q/k between the projection and the
                # kernel (which forfeits the packed kernel's zero-copy
                # read — one layout copy, the price of rotation)
                qkv_t = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # [3,B,H,S,D]
                q, k, v = qkv_t[0], qkv_t[1], qkv_t[2]
                if self.rope:
                    cos, sin = _rope_tables(S, D)
                    cos = jnp.asarray(cos, x.dtype)[None, None]
                    sin = jnp.asarray(sin, x.dtype)[None, None]
                    # positionwise over the GLOBAL sequence, so under a
                    # sequence scope GSPMD shards the rotation with the
                    # activations — ring semantics are unchanged
                    q = _apply_rope(q, cos, sin)
                    k = _apply_rope(k, cos, sin)
                if scope is not None:
                    out = ring_mha(q, k, v, causal=self.causal, scope=scope)
                else:
                    out = flash_attention(q, k, v, causal=self.causal)
                out = jnp.reshape(
                    jnp.transpose(out, (0, 2, 1, 3)), (B, S, H * D)
                )
            else:
                # packed-layout kernel (r4): q/k/v are read straight
                # from the fused projection and the output lands
                # sequence-major — the bhsd transposes (the top copy
                # kernels in the r4 transformer trace, fwd AND their
                # bwd counterparts) never materialize
                out = flash_attention_qkv(qkv, causal=self.causal)
                out = jnp.reshape(out, (B, S, H * D))
            return self.proj(out)

        def get_config(self):
            config = super().get_config()
            config.update(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                causal=self.causal,
                rope=self.rope,
            )
            return config

    _FLASH_MHA_CLS = FlashMHA
    return FlashMHA


_FUSED_LN_CLS = None


def _fused_ln_layer():
    """The FusedLayerNorm layer class (lazy, serializer-registered —
    same pattern as :func:`_flash_mha_layer`). Normalization runs the
    one-pass Pallas kernel (:mod:`elephas_tpu.ops.layer_norm`): the r4
    trace billed ~20% of transformer device time to XLA's multi-pass
    layernorm fusions + their bf16↔f32 converts (VERDICT r4 #3a).
    Under a sequence-parallel scope the math falls back to plain jnp
    ops so GSPMD shards the normalization with the seq-sharded
    activations instead of forcing the kernel replicated."""
    global _FUSED_LN_CLS
    if _FUSED_LN_CLS is not None:
        return _FUSED_LN_CLS
    import keras

    @keras.saving.register_keras_serializable(package="elephas_tpu")
    class FusedLayerNorm(keras.layers.Layer):
        """LayerNormalization (last axis, keras-equivalent math: f32
        statistics, affine gamma/beta) over one fused Pallas pass."""

        def __init__(self, epsilon: float = 1e-6, **kwargs):
            super().__init__(**kwargs)
            self.epsilon = float(epsilon)

        def build(self, input_shape):
            d = int(input_shape[-1])
            self.gamma = self.add_weight(
                name="gamma", shape=(d,), initializer="ones"
            )
            self.beta = self.add_weight(
                name="beta", shape=(d,), initializer="zeros"
            )
            super().build(input_shape)

        def call(self, x):
            import jax
            import jax.numpy as jnp

            from elephas_tpu.parallel.sequence import (
                active_sequence_scope,
            )

            gamma, beta = self.gamma.value, self.beta.value
            if active_sequence_scope() is not None:
                x32 = jnp.asarray(x, jnp.float32)
                mean = jnp.mean(x32, axis=-1, keepdims=True)
                xc = x32 - mean
                var = jnp.mean(xc * xc, axis=-1, keepdims=True)
                y = xc * jax.lax.rsqrt(var + self.epsilon)
                return (y * gamma + beta).astype(x.dtype)

            from elephas_tpu.ops.layer_norm import layer_norm

            return layer_norm(x, gamma, beta, eps=self.epsilon)

        def compute_output_shape(self, input_shape):
            # keras's symbolic build traces call() with a polymorphic
            # batch dim otherwise — the kernel's row flatten needs
            # concrete rows (shape is identity anyway)
            return input_shape

        def get_config(self):
            config = super().get_config()
            config.update(epsilon=self.epsilon)
            return config

    _FUSED_LN_CLS = FusedLayerNorm
    return FusedLayerNorm


def __getattr__(name):
    # `from elephas_tpu.models.transformer import FlashMHA` resolves to
    # the real (lazily created) layer class
    if name == "FlashMHA":
        return _flash_mha_layer()
    if name == "FusedLayerNorm":
        return _fused_ln_layer()
    raise AttributeError(name)


def _block(x, num_heads, head_dim, mlp_ratio, dropout, causal, name, L,
           FlashMHA, rope=False):
    # keras LayerNormalization on purpose, A/B-measured (r5): the
    # in-tree Pallas FusedLayerNorm (one-pass fwd, one-pass bwd with
    # in-kernel dgamma/dbeta) reaches only PARITY end-to-end on v5e
    # (220.4-221.4k tok/s fused vs 221.9-223.0k keras-LN, same
    # session) — both run at the platform's realized elementwise
    # bandwidth, so the simpler stock layer wins on compatibility.
    # FusedLayerNorm stays available (elephas_tpu.models) for shapes
    # where a single fused pass wins.
    h = L.LayerNormalization(epsilon=1e-6, name=f"{name}_ln1")(x)
    h = FlashMHA(num_heads, head_dim, causal=causal, rope=rope,
                 name=f"{name}_attn")(h)
    if dropout > 0:
        # rate-0 Dropout layers are elided entirely: dead ops, and their
        # python `if training` branch breaks keras.RematScope (jax.remat
        # traces the training flag)
        h = L.Dropout(dropout, name=f"{name}_drop1")(h)
    x = L.Add(name=f"{name}_res1")([x, h])
    h = L.LayerNormalization(epsilon=1e-6, name=f"{name}_ln2")(x)
    d_model = x.shape[-1]
    h = L.Dense(int(d_model * mlp_ratio), activation="gelu", name=f"{name}_mlp1")(h)
    h = L.Dense(d_model, name=f"{name}_mlp2")(h)
    if dropout > 0:
        h = L.Dropout(dropout, name=f"{name}_drop2")(h)
    return L.Add(name=f"{name}_res2")([x, h])


def _positions(maxlen: int, d_model: int) -> np.ndarray:
    """Sinusoidal position table (fixed, not learned — no extra state)."""
    pos = np.arange(maxlen)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    table = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return table.astype(np.float32)


@functools.lru_cache(maxsize=8)
def _rope_tables(maxlen: int, head_dim: int):
    """cos/sin tables ``[S, D]`` for rotary position embeddings
    (half-split / GPT-NeoX convention; ``head_dim`` must be even).
    Cached so every attention layer shares ONE host table (and jax sees
    one constant object) instead of L identical copies — at long-context
    sequence lengths the table is large (code-review r4)."""
    inv = 1.0 / (10000.0 ** (np.arange(0, head_dim, 2) / head_dim))
    ang = np.arange(maxlen)[:, None] * inv[None, :]  # [S, D/2]
    cos = np.concatenate([np.cos(ang), np.cos(ang)], axis=-1)
    sin = np.concatenate([np.sin(ang), np.sin(ang)], axis=-1)
    return cos.astype(np.float32), sin.astype(np.float32)


def _apply_rope(x, cos, sin):
    """Rotate ``[..., S, D]`` (or ``[..., D]`` single-position) heads:
    ``x·cos + rotate_half(x)·sin`` with broadcastable tables."""
    import jax.numpy as jnp

    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rot * sin


def transformer_classifier(
    vocab_size: int = 20000,
    maxlen: int = 128,
    num_classes: int = 2,
    d_model: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    mlp_ratio: float = 4.0,
    dropout: float = 0.1,
    lr: float = 1e-3,
    seed: int = 0,
    dtype_policy: str | None = None,
):
    """Encoder-stack text classifier (IMDB-class tasks, BASELINE #4+).

    ``dtype_policy='mixed_bfloat16'`` keeps the matmuls (and the flash
    attention kernel) in bf16 on the MXU with float32 variables."""
    keras = _keras()
    keras.utils.set_random_seed(seed)
    with _dtype_policy_scope(keras, dtype_policy):
        L = keras.layers
        FlashMHA = _flash_mha_layer()
        head_dim = d_model // num_heads

        inputs = keras.Input((maxlen,), dtype="int32")
        x = L.Embedding(vocab_size, d_model, name="tok_embed")(inputs)
        x = x + _positions(maxlen, d_model)[None]
        for b in range(num_layers):
            x = _block(
                x, num_heads, head_dim, mlp_ratio, dropout, False,
                f"blk{b}", L, FlashMHA,
            )
        x = L.LayerNormalization(epsilon=1e-6, name="final_ln")(x)
        x = L.GlobalAveragePooling1D(name="pool")(x)
        activation = "sigmoid" if num_classes == 1 else "softmax"
        outputs = L.Dense(
            num_classes, activation=activation, name="head", dtype="float32"
        )(x)
        model = keras.Model(inputs, outputs, name="transformer_classifier")
    loss = (
        "binary_crossentropy"
        if num_classes == 1
        else "sparse_categorical_crossentropy"
    )
    model.compile(
        optimizer=keras.optimizers.Adam(lr), loss=loss, metrics=["accuracy"]
    )
    return model


def transformer_lm(
    vocab_size: int = 32000,
    maxlen: int = 256,
    d_model: int = 256,
    num_heads: int = 4,
    num_layers: int = 4,
    mlp_ratio: float = 4.0,
    dropout: float = 0.0,
    lr: float = 3e-4,
    seed: int = 0,
    dtype_policy: str | None = None,
    rope: bool = False,
):
    """Decoder-only causal LM (next-token prediction).

    ``dtype_policy='mixed_bfloat16'`` keeps the matmuls (and the flash
    attention kernel) in bf16 on the MXU; the lm_head logits stay f32.
    ``rope=True`` (r4) uses rotary position embeddings in every
    attention layer instead of the additive sinusoidal table — the
    modern-LLM positional scheme; composes with the sequence-parallel
    ring (rotation is positionwise over the global sequence) and with
    KV-cache decode."""
    keras = _keras()
    keras.utils.set_random_seed(seed)
    with _dtype_policy_scope(keras, dtype_policy):
        L = keras.layers
        FlashMHA = _flash_mha_layer()
        head_dim = d_model // num_heads

        inputs = keras.Input((maxlen,), dtype="int32")
        x = L.Embedding(vocab_size, d_model, name="tok_embed")(inputs)
        if not rope:
            x = x + _positions(maxlen, d_model)[None]
        for b in range(num_layers):
            x = _block(
                x, num_heads, head_dim, mlp_ratio, dropout, True,
                f"blk{b}", L, FlashMHA, rope=rope,
            )
        x = L.LayerNormalization(epsilon=1e-6, name="final_ln")(x)
        outputs = L.Dense(vocab_size, name="lm_head", dtype="float32")(x)
        model = keras.Model(inputs, outputs, name="transformer_lm")
    model.compile(
        optimizer=keras.optimizers.Adam(lr),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )
    return model


def _sample_logits(logits, key, temperature: float, top_k, top_p=None):
    """Greedy argmax at temperature 0; else temperature-scaled
    categorical sampling, optionally truncated to the top_k logits
    and/or the top_p (nucleus) probability mass.
    Shared by the full-recompute and KV-cache decode paths."""
    import jax
    import jax.numpy as jnp

    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _filter_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def _filter_logits(scaled, top_k, top_p):
    """top-k / top-p (nucleus) truncation of ``[B, V]`` scaled logits —
    ONE implementation shared by the scalar-temperature sampler above
    and the serving engine's vector-temperature sampler, so the two
    paths cannot drift apart (their parity is a documented contract)."""
    import jax
    import jax.numpy as jnp

    if top_k is not None:
        kth = jnp.sort(scaled, axis=-1)[:, -int(top_k)][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None:
        # nucleus: keep the smallest set of tokens whose cumulative
        # probability reaches top_p (the first token past the threshold
        # is kept so the nucleus is never empty)
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < float(top_p)  # prev-cumulative below mass
        # threshold = smallest kept logit per row
        kept_min = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        scaled = jnp.where(scaled < kept_min, -jnp.inf, scaled)
    return scaled


def _mesh_fingerprint(mesh, batch_axes, model_axis):
    """Hashable identity of a decode mesh for the jit cache — axis
    layout plus the concrete device set (hyperparam trials lease many
    distinct submeshes over the same process lifetime)."""
    if mesh is None:
        return None
    return (
        tuple(mesh.shape.items()),
        tuple(d.id for d in mesh.devices.flat),
        batch_axes,
        model_axis,
    )


def _decode_jit_cache(model) -> dict:
    """The per-model compiled-decode cache, BOUNDED via
    :func:`_cache_insert`: mesh-fingerprinted keys would otherwise pin
    every leased submesh (devices + compiled executables) alive for the
    model's lifetime (hyperparam trials lease many)."""
    return model.__dict__.setdefault("_elephas_generate_jit", {})


def _cache_insert(cache: dict, key, value, bound: int = 16):
    """Insert then evict oldest entries past ``bound`` — eviction AFTER
    insertion so the entry being served is never the one popped
    (code-review r5: pre-insert eviction recompiled the round-robin
    17th config on every call)."""
    cache[key] = value
    while len(cache) > bound:
        cache.pop(next(iter(cache)))


def _cache_get(cache: dict, key):
    """Fetch AND refresh recency: the hit re-inserts at the dict's end,
    so :func:`_cache_insert`'s evict-oldest approximates LRU instead of
    FIFO — a hot decode config inserted early is no longer silently
    evicted (and recompiled) once 16 newer configs appear (ADVICE r5)."""
    value = cache.get(key)
    if value is not None:
        cache[key] = cache.pop(key)
    return value


def _finish_decode(model, run, wargs, tokens0, key, mesh, batch_axes,
                   n_rows, n_cols):
    """Shared decode epilogue: stage the tokens/key (sharded under
    ``mesh`` if given), execute the compiled loop, record the
    out-sharding introspection hook, and host-read the real rows."""
    import jax.numpy as jnp

    if mesh is None:
        out = run(*wargs, jnp.asarray(tokens0), key)
        model.__dict__["_elephas_generate_out_sharding"] = getattr(
            out, "sharding", None
        )
        return np.asarray(out[:n_rows, :n_cols])

    from jax.sharding import NamedSharding, PartitionSpec as P

    from elephas_tpu.parallel.mesh import host_read, put_global

    tokens = put_global(tokens0, NamedSharding(mesh, P(batch_axes)))
    out = run(
        *wargs, tokens,
        put_global(np.asarray(key), NamedSharding(mesh, P())),
    )
    # introspection hook: tests (and curious users) can check the decode
    # really ran batch-sharded rather than replicated
    model.__dict__["_elephas_generate_out_sharding"] = out.sharding
    return host_read(out, mesh)[:n_rows, :n_cols]


def _validate_decode_args(model, prompt, steps, top_k, top_p):
    """Shared decode-argument validation (also used by the pipeline
    ring decode): normalizes the prompt to ``[B, P]`` and checks the
    length/sampling bounds against the model. Returns
    ``(prompt, b, p, maxlen, vocab)``."""
    prompt = np.asarray(prompt)
    if prompt.ndim == 1:
        prompt = prompt[None]
    b, p = prompt.shape
    maxlen = int(model.inputs[0].shape[1])
    vocab = int(model.outputs[0].shape[-1])
    if p + steps > maxlen:
        raise ValueError(
            f"prompt ({p}) + steps ({steps}) exceeds the model's "
            f"maxlen ({maxlen})"
        )
    if top_k is not None and not 0 < int(top_k) <= vocab:
        raise ValueError(
            f"top_k={top_k} outside (0, vocab={vocab}]"
        )
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p={top_p} outside (0, 1]")
    return prompt, b, p, maxlen, vocab


def _decode_shardings(variables, mesh, model_axis, rules):
    """Per-variable NamedShardings for decoding under ``mesh``: the TP
    planner's layouts when a >1 ``model_axis`` exists, replicated
    otherwise (data/seq/stage axes shard the batch, never weights)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if model_axis is not None and mesh.shape.get(model_axis, 1) > 1:
        from elephas_tpu.parallel.tensor import plan_sharding

        return plan_sharding(
            variables, mesh, model_axis=model_axis, rules=rules
        )
    return [NamedSharding(mesh, P())] * len(variables)


def generate(
    model,
    prompt,
    steps: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    seed: int = 0,
    kv_cache: bool = False,
    mesh=None,
    batch_axes=("data",),
    model_axis: str | None = None,
    rules=None,
):
    """Autoregressive sampling from a :func:`transformer_lm` model.

    ``prompt``: ``[B, P]`` int tokens (``P + steps`` must fit the
    model's ``maxlen``). Returns ``[B, P + steps]`` tokens.
    ``temperature=0`` is greedy argmax; otherwise softmax sampling at
    that temperature, optionally truncated to the ``top_k`` most likely
    tokens and/or the ``top_p`` nucleus (the smallest set of tokens
    whose cumulative probability reaches ``top_p``).

    TPU-shaped: ONE jitted program — the sequence stays at the model's
    fixed ``maxlen`` (causal attention makes positions ``>= t`` inert),
    and ``lax.fori_loop`` advances a token at a time writing in place.
    The default path recomputes the prefix each step (O(S²·L) per
    token, exactly the training math); ``kv_cache=True`` switches to a
    cached decode — per-layer K/V caches, one token's compute per step
    (O(S·L) total) — same greedy outputs, built for
    :func:`transformer_lm`'s architecture specifically.

    Mesh-aware decode (r5, VERDICT r4 #1 — the LM analogue of the
    reference's distributed ``predict``, SURVEY.md §3.4): pass ``mesh``
    and the decode runs as ONE GSPMD program over it — the batch shards
    over ``batch_axes`` (padded up to their product and sliced back),
    and with a >1 ``model_axis`` the weights stay sharded through
    ``stateless_call`` under the TP planner's layouts (qkv
    column-split, proj row-split, vocab-sharded head — ``rules``
    overrides), so models that only fit sharded can decode at all.
    Under ``kv_cache=True`` the per-layer K/V caches shard batch over
    ``batch_axes`` and heads over ``model_axis``. Weights ride as jit
    arguments (host→mesh upload per call — decode loops dominate, the
    upload does not). Every gang process must make the identical call
    (SPMD contract); all return the full tokens.
    """
    import jax
    import jax.numpy as jnp

    prompt, b, p, maxlen, _vocab = _validate_decode_args(
        model, prompt, steps, top_k, top_p
    )

    pad = 0
    if mesh is not None:
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        batch_axes = tuple(batch_axes)
        missing = [a for a in batch_axes if a not in mesh.shape]
        if missing:
            raise ValueError(
                f"batch_axes {missing} not in mesh axes "
                f"{tuple(mesh.shape)}"
            )
        dp = int(np.prod([mesh.shape[a] for a in batch_axes]))
        pad = (-b) % dp
    bt = b + pad
    tokens0 = np.zeros((bt, maxlen), np.int32)
    tokens0[:b, :p] = prompt
    if pad:
        # padded lanes decode real math on a copy of the last prompt row
        # (any in-vocab content works — they are sliced off below)
        tokens0[b:, :p] = prompt[-1]

    if kv_cache:
        return _generate_cached(
            model, tokens0, bt, p, steps, temperature, top_k, top_p, seed,
            mesh=mesh, batch_axes=batch_axes, model_axis=model_axis,
            rules=rules, n_real=b,
        )

    tv = [v.value for v in model.trainable_variables]
    ntv = [v.value for v in model.non_trainable_variables]

    # the compiled loop is cached ON the model, keyed by everything its
    # program shape depends on — repeat calls (same prompt shape and
    # sampling config) hit the cache, and weights ride as ARGUMENTS so
    # further training never serves stale baked-in constants
    cache = _decode_jit_cache(model)
    cache_key = (
        bt, p, steps, float(temperature), top_k, top_p,
        _mesh_fingerprint(mesh, batch_axes, model_axis),
    )
    run = _cache_get(cache, cache_key)
    if run is None:

        @jax.jit
        def run(tv, ntv, tokens, key):
            def step(t, carry):
                tokens, key = carry
                logits, _ = model.stateless_call(
                    tv, ntv, tokens, training=False
                )
                key, sub = jax.random.split(key)
                nxt = _sample_logits(
                    logits[:, t - 1], sub, temperature, top_k, top_p
                )
                return tokens.at[:, t].set(nxt), key

            tokens, _ = jax.lax.fori_loop(p, p + steps, step, (tokens, key))
            return tokens

        _cache_insert(cache, cache_key, run)

    if mesh is not None:
        from elephas_tpu.parallel.mesh import put_global

        tv_sh = _decode_shardings(
            model.trainable_variables, mesh, model_axis, rules
        )
        ntv_sh = _decode_shardings(
            model.non_trainable_variables, mesh, model_axis, rules
        )
        tv = [put_global(np.asarray(v), s) for v, s in zip(tv, tv_sh)]
        ntv = [put_global(np.asarray(v), s) for v, s in zip(ntv, ntv_sh)]
    return _finish_decode(
        model, run, (tv, ntv), tokens0, jax.random.PRNGKey(seed),
        mesh, batch_axes, b, p + steps,
    )



def validate_token_decode_model(model, what: str = "kv_cache decode",
                                hint: str = "use kv_cache=False",
                                allow_stock: bool = True):
    """Compatibility gate for token-at-a-time cached decode, shared by
    ``generate(kv_cache=True)`` and the serving engine
    (:mod:`elephas_tpu.serving`): the model must be a single-input
    functional graph of causal attention layers plus token-local
    layers, computed in float32, with no weight-tied or nested
    attention call sites. Returns ``(flash_layers, stock_mha_layers,
    gqa_layers)``; raises ``ValueError`` (messages prefixed ``what``,
    suffixed ``hint``) otherwise. ``allow_stock=False`` additionally
    rejects stock keras MultiHeadAttention/GQA layers (callers whose
    decode handlers only replay ``FlashMHA`` math)."""
    import keras

    FlashMHA = _flash_mha_layer()

    if not hasattr(model, "_run_through_graph") or len(model.inputs) != 1:
        raise ValueError(
            f"{what} needs a single-input functional model; {hint} "
            f"for this architecture"
        )
    flash_layers = [
        l for l in model._flatten_layers() if isinstance(l, FlashMHA)
    ]
    gqa_cls = getattr(
        keras.layers, "GroupQueryAttention", None
    ) or getattr(keras.layers, "GroupedQueryAttention", None)

    def _stock_layers_of(base):
        if base is None:
            return []
        found = []
        for l in model._flatten_layers():
            if not isinstance(l, base):
                continue
            if not allow_stock:
                raise ValueError(
                    f"{what} replays FlashMHA attention only, but "
                    f"{l.name!r} is a stock {base.__name__}; {hint}"
                )
            # the decode handler recomputes STOCK attention math from
            # the EinsumDense kernels; a subclass overriding call /
            # _compute_attention (RoPE, ALiBi, soft-caps...) would
            # silently decode different tokens — reject with guidance
            # (code-review r4)
            if (
                type(l).call is not base.call
                or type(l)._compute_attention is not base._compute_attention
            ):
                raise ValueError(
                    f"{what} replays stock {base.__name__} math, "
                    f"but {l.name!r} is a customized subclass "
                    f"({type(l).__name__}); {hint}"
                )
            if len(l._output_dense.kernel.shape) != 3:
                raise ValueError(
                    f"{what}: {l.name!r} has a non-default "
                    f"output_shape (rank-"
                    f"{len(l._output_dense.kernel.shape)} output "
                    f"kernel); {hint}"
                )
            found.append(l)
        return found

    stock_mha_layers = _stock_layers_of(keras.layers.MultiHeadAttention)
    gqa_layers = _stock_layers_of(gqa_cls)
    if not flash_layers and not stock_mha_layers and not gqa_layers:
        raise ValueError(
            f"{what} needs at least one attention layer (FlashMHA"
            + (", keras MultiHeadAttention, or GroupQueryAttention"
               if allow_stock else "")
            + f" — the cache lives there); {hint}"
        )
    for l in flash_layers:
        if not l.causal:
            raise ValueError(
                f"{what} is causal by construction, but FlashMHA "
                f"layer {l.name!r} has causal=False; {hint}"
            )
    # count call sites within THIS model's graph only — inbound nodes
    # accumulate across every symbolic call a layer ever received, so a
    # layer also referenced by some other Model would be spuriously
    # rejected by a global count (code-review r4)
    calls_here: dict[int, int] = {}
    nodes_by_depth = getattr(model, "_nodes_by_depth", None)
    if nodes_by_depth is None:  # fall back to the (global) node count
        for l in flash_layers + stock_mha_layers + gqa_layers:
            calls_here[id(l)] = len(l._inbound_nodes)
    else:
        for depth_nodes in nodes_by_depth.values():
            for node in depth_nodes:
                op = getattr(node, "operation", None)
                if op is not None:
                    calls_here[id(op)] = calls_here.get(id(op), 0) + 1
    for l in flash_layers + stock_mha_layers + gqa_layers:
        n_calls = calls_here.get(id(l), 0)
        if n_calls > 1:
            # weight-tied reuse (ALBERT-style): every call site would
            # share ONE name-keyed cache and clobber the others' K/V
            raise ValueError(
                f"{what} keys K/V caches by layer, but "
                f"{l.name!r} is called at {n_calls} graph "
                f"nodes (weight tying) — the call sites would corrupt "
                f"each other's cache; {hint}"
            )
        if n_calls == 0 and nodes_by_depth is not None:
            # reachable only through a NESTED sub-Model's graph: the
            # decode handler would never intercept it (the replay calls
            # the inner Model as one opaque layer) — reject with
            # guidance instead of dying mid-trace (code-review r4)
            raise ValueError(
                f"{what}: attention layer {l.name!r} lives "
                f"inside a nested sub-Model — the token-by-token replay "
                f"only walks the top-level graph; flatten the model or "
                f"{hint}"
            )
    _SEQ_MIXING = (
        keras.layers.GlobalAveragePooling1D, keras.layers.AveragePooling1D,
        keras.layers.MaxPooling1D, keras.layers.Conv1D, keras.layers.RNN,
        keras.layers.Flatten,
    )
    for l in model._flatten_layers():
        if isinstance(l, _SEQ_MIXING):
            raise ValueError(
                f"{what} replays the graph one token at a time; "
                f"layer {l.name!r} ({type(l).__name__}) mixes the "
                f"sequence axis — {hint}"
            )
    compute_dtype = getattr(model.dtype_policy, "compute_dtype", "float32")
    if compute_dtype != "float32":
        raise ValueError(
            f"{what} computes in float32, which would diverge "
            f"from this model's {compute_dtype} forward (argmax flips "
            f"where top logits are close) — {hint} for "
            f"mixed-precision models"
        )
    return flash_layers, stock_mha_layers, gqa_layers


def _generate_cached(model, tokens0, b, p, steps, temperature, top_k,
                     top_p, seed, mesh=None, batch_axes=("data",),
                     model_axis=None, rules=None, n_real=None):
    """KV-cache decode for ANY single-input causal LM assembled from
    ``FlashMHA`` attention plus token-local keras layers.

    r4 (VERDICT r3 weak #3): instead of requiring ``transformer_lm``'s
    exact variable paths, the model's functional graph is replayed one
    TOKEN at a time through keras' own node traversal
    (``Function._run_through_graph``), each node's operation swapped for
    a single-token decode handler:

    - ``FlashMHA`` — and stock ``keras.layers.MultiHeadAttention``
      called self-attentively with ``use_causal_mask=True`` (r4) —
      become cached-attention read/writes: per-layer ``[B, S, H, Dh]``
      K/V caches keyed by layer name, one token's q/k/v computed and
      attention taken over the cache (O(S·L) for the whole generation
      vs the default path's O(S²·L));
    - layers with weights run ``stateless_call`` on the ``[B, D]`` token
      activations, weights riding as jit ARGUMENTS so further training
      never serves stale baked-in constants;
    - ``Dropout`` is elided (inference);
    - weightless ops (residual ``Add``s, the positional-table add) run
      as recorded, with any concrete array argument spanning the
      sequence axis sliced at ``t`` (that is how the fixed sinusoidal
      table follows the decode position).

    One jitted ``fori_loop`` runs prefill and sampling alike (prompt
    positions keep their ground-truth token; sampled positions write in
    place), the compiled loop caching on the model like the default
    path. Graph shapes the token-local replay cannot honor — no causal
    ``FlashMHA``, mixed precision, sequence-mixing layers (pooling,
    conv, RNNs) — raise with a pointer to ``kv_cache=False``.
    """
    import jax
    import jax.numpy as jnp

    import keras

    FlashMHA = _flash_mha_layer()

    flash_layers, stock_mha_layers, gqa_layers = validate_token_decode_model(
        model, what="kv_cache decode", hint="use kv_cache=False"
    )
    gqa_cls = getattr(
        keras.layers, "GroupQueryAttention", None
    ) or getattr(keras.layers, "GroupedQueryAttention", None)

    maxlen = tokens0.shape[1]
    total = p + steps

    if mesh is None:
        weights = {v.path: v.value for v in model.variables}

        def _constrain_cache(z, heads):
            return z
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import put_global

        var_sh = _decode_shardings(
            list(model.variables), mesh, model_axis, rules
        )
        weights = {
            v.path: put_global(np.asarray(v.value), s)
            for v, s in zip(model.variables, var_sh)
        }

        def _constrain_cache(z, heads):
            # [B, S, H, Dh] K/V cache: batch over the batch axes, heads
            # over the model axis when they tile (GQA kv-head counts may
            # not divide — those caches stay head-replicated)
            ax = (
                model_axis
                if model_axis is not None
                and mesh.shape.get(model_axis, 1) > 1
                and heads % mesh.shape[model_axis] == 0
                else None
            )
            return jax.lax.with_sharding_constraint(
                z, NamedSharding(mesh, P(batch_axes, None, ax, None))
            )

    cache = _decode_jit_cache(model)
    cache_key = (
        "kv", b, p, steps, float(temperature), top_k, top_p,
        _mesh_fingerprint(mesh, batch_axes, model_axis),
    )
    run = _cache_get(cache, cache_key)
    if run is None:

        def _slice_seq(a):
            # CONCRETE array arguments recorded in the graph that span
            # the sequence axis follow the decode position: a
            # [..., maxlen, D] table (sinusoidal positions) slices to
            # [..., D]; a [maxlen] index vector (arange feeding a
            # learned positional Embedding) slices to the scalar t.
            # Traced tensors are never touched — their dims can
            # coincide with maxlen without meaning "sequence".
            concrete = isinstance(a, np.ndarray) or (
                isinstance(a, jax.Array)
                and not isinstance(a, jax.core.Tracer)
            )
            if not concrete:
                return a
            if a.ndim >= 2 and a.shape[-2] == maxlen:
                return jnp.asarray(a)[..., t_ref[0], :]
            if a.ndim == 1 and a.shape[0] == maxlen:
                return jnp.asarray(a)[t_ref[0]]
            return a

        t_ref = [None]  # current decode position, set per decode_step

        def decode_step(w, tok, t, caches):
            t_ref[0] = t
            ctx_new = {}

            def handler(op):
                if isinstance(op, FlashMHA):
                    def attn(x, *_a, **_k):
                        ck, cv = caches[op.name]
                        H, Dh = op.num_heads, op.head_dim
                        qkv = x @ w[op.qkv.kernel.path]  # [B, 3·H·Dh]
                        q, k, v = jnp.split(
                            qkv.reshape(x.shape[0], 3, H, Dh), 3, axis=1
                        )
                        q, k, v = q[:, 0], k[:, 0], v[:, 0]  # [B, H, Dh]
                        if getattr(op, "rope", False):
                            # rotate THIS position's q and k before they
                            # enter the cache/attend — cached k stay
                            # rotated, matching the full forward
                            cos_np, sin_np = _rope_tables(maxlen, Dh)
                            cos_t = jnp.asarray(cos_np)[t]
                            sin_t = jnp.asarray(sin_np)[t]
                            q = _apply_rope(q, cos_t, sin_t)
                            k = _apply_rope(k, cos_t, sin_t)
                        ck = ck.at[:, t].set(k)
                        cv = cv.at[:, t].set(v)
                        att = jnp.einsum("bhd,bshd->bhs", q, ck) * (
                            Dh**-0.5
                        )
                        visible = jnp.arange(maxlen)[None, None, :] <= t
                        att = jax.nn.softmax(
                            jnp.where(visible, att, -jnp.inf), axis=-1
                        )
                        o = jnp.einsum("bhs,bshd->bhd", att, cv).reshape(
                            x.shape[0], H * Dh
                        )
                        ctx_new[op.name] = (ck, cv)
                        return (
                            o @ w[op.proj.kernel.path]
                            + w[op.proj.bias.path]
                        )

                    return attn
                if isinstance(op, keras.layers.MultiHeadAttention) or (
                    gqa_cls is not None and isinstance(op, gqa_cls)
                ):
                    def attn_stock(query, *pos, _op=op, **kwargs):
                        if not kwargs.get("use_causal_mask"):
                            raise ValueError(
                                f"kv_cache decode: stock attention layer "
                                f"{_op.name!r} is called without "
                                f"use_causal_mask=True — non-causal "
                                f"attention cannot decode token-by-"
                                f"token; use kv_cache=False"
                            )
                        value = pos[0] if pos else kwargs.get("value")
                        key_in = (
                            pos[1] if len(pos) > 1 else kwargs.get("key")
                        )
                        if value is not query or (
                            key_in is not None and key_in is not query
                        ):
                            raise ValueError(
                                f"kv_cache decode: {_op.name!r} is used "
                                f"as cross-attention; use kv_cache=False"
                            )
                        for bad in ("attention_mask", "query_mask",
                                    "value_mask", "key_mask"):
                            if kwargs.get(bad) is not None:
                                raise ValueError(
                                    f"kv_cache decode: {_op.name!r} "
                                    f"carries an explicit {bad}; use "
                                    f"kv_cache=False"
                                )
                        if kwargs.get("return_attention_scores"):
                            raise ValueError(
                                f"kv_cache decode: {_op.name!r} returns "
                                f"attention scores; use kv_cache=False"
                            )

                        def dense(sub, x_, eq_in, eq_out):
                            y = jnp.einsum(
                                f"{eq_in}->{eq_out}", x_,
                                w[sub.kernel.path],
                            )
                            if sub.bias is not None:
                                y = y + w[sub.bias.path]
                            return y

                        x = query  # [B, D]
                        q = dense(_op._query_dense, x, "bd,dhk", "bhk")
                        k = dense(_op._key_dense, x, "bd,dhk", "bhk")
                        v = dense(_op._value_dense, x, "bd,dhv", "bhv")
                        ck, cv = caches[_op.name]
                        ck = ck.at[:, t].set(k)
                        cv = cv.at[:, t].set(v)
                        inv = getattr(_op, "_inverse_sqrt_key_dim", None)
                        if inv is None:  # GQA names it by head_dim
                            inv = _op._inverse_sqrt_head_dim
                        # one grouped attend covers both: the cache holds
                        # UN-repeated kv heads and query heads attend in
                        # groups of rep (rep == 1 for plain MHA). keras
                        # multiplies the QUERY by the inverse-sqrt factor
                        # BEFORE the dot — matching that operation order
                        # keeps the float reduction identical to the
                        # full-recompute path (code-review r4)
                        hq, hkv = q.shape[1], k.shape[1]
                        rep = hq // hkv
                        qg = (q * float(inv)).reshape(
                            q.shape[0], hkv, rep, q.shape[-1]
                        )
                        att = jnp.einsum("bgrk,bsgk->bgrs", qg, ck)
                        visible = (
                            jnp.arange(maxlen)[None, None, None, :] <= t
                        )
                        att = jax.nn.softmax(
                            jnp.where(visible, att, -jnp.inf), axis=-1
                        )
                        ctx = jnp.einsum(
                            "bgrs,bsgv->bgrv", att, cv
                        ).reshape(q.shape[0], hq, cv.shape[-1])
                        ctx_new[_op.name] = (ck, cv)
                        return dense(
                            _op._output_dense, ctx, "bhv,hvd", "bd"
                        )

                    return attn_stock
                if isinstance(op, keras.layers.Dropout):
                    return lambda x, *a, **k: x
                if isinstance(op, keras.Layer) and op.variables:
                    def stateless(*args, _op=op, **kwargs):
                        if kwargs.get("training"):
                            kwargs["training"] = False
                        args = [_slice_seq(a) for a in args]
                        tv = [w[v.path] for v in _op.trainable_variables]
                        ntv = [
                            w[v.path]
                            for v in _op.non_trainable_variables
                        ]
                        out, _ = _op.stateless_call(tv, ntv, *args, **kwargs)
                        return out

                    return stateless

                def weightless(*args, _op=op, **kwargs):
                    args = [_slice_seq(a) for a in args]
                    kwargs = {kk: _slice_seq(vv) for kk, vv in kwargs.items()}
                    return _op(*args, **kwargs)

                return weightless

            logits = model._run_through_graph(tok, operation_fn=handler)
            return logits, {
                name: ctx_new.get(name, caches[name]) for name in caches
            }

        @jax.jit
        def run(w, tokens, key):
            caches = {
                l.name: (
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l.num_heads, l.head_dim),
                            jnp.float32,
                        ),
                        l.num_heads,
                    ),
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l.num_heads, l.head_dim),
                            jnp.float32,
                        ),
                        l.num_heads,
                    ),
                )
                for l in flash_layers
            }
            for l in stock_mha_layers:
                caches[l.name] = (
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l._num_heads, l._key_dim),
                            jnp.float32,
                        ),
                        l._num_heads,
                    ),
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l._num_heads,
                             l._value_dim or l._key_dim),
                            jnp.float32,
                        ),
                        l._num_heads,
                    ),
                )
            for l in gqa_layers:
                caches[l.name] = (
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l.num_key_value_heads, l.head_dim),
                            jnp.float32,
                        ),
                        l.num_key_value_heads,
                    ),
                    _constrain_cache(
                        jnp.zeros(
                            (b, maxlen, l.num_key_value_heads, l.head_dim),
                            jnp.float32,
                        ),
                        l.num_key_value_heads,
                    ),
                )

            def step(t, carry):
                tokens, caches, key = carry
                logits, caches = decode_step(w, tokens[:, t], t, caches)
                # prompt positions keep their ground-truth token; only
                # the continuation writes
                write = t + 1 >= p
                # advance the PRNG stream only on sampling steps — the
                # default (kv_cache=False) path splits once per GENERATED
                # token, so consuming splits during prefill would make
                # sampled output at the same seed differ between the two
                # paths (r3 advisor finding)
                key2, sub = jax.random.split(key)
                key = jnp.where(write, key2, key)
                nxt = _sample_logits(logits, sub, temperature, top_k, top_p)
                tokens = jnp.where(
                    write,
                    tokens.at[:, jnp.minimum(t + 1, maxlen - 1)].set(nxt),
                    tokens,
                )
                return tokens, caches, key

            tokens, _, _ = jax.lax.fori_loop(
                0, total - 1, step, (tokens, caches, key)
            )
            return tokens

        _cache_insert(cache, cache_key, run)

    return _finish_decode(
        model, run, (weights,), tokens0, jax.random.PRNGKey(seed),
        mesh, batch_axes, b if n_real is None else n_real, total,
    )
