"""Switch/GShard-style Mixture-of-Experts transformer — zoo member.

The reference has no MoE (SURVEY.md §2a lists expert parallelism as
absent); this is the TPU-native extension in its user-facing form:

- :class:`MoeFFN` — a Keras layer wrapping the routing/capacity math of
  :mod:`elephas_tpu.ops.moe` (top-k routing, Switch §2.2 load-balance
  auxiliary loss via ``add_loss``), so any ``SparkModel``-trained model
  can use experts.
- :func:`switch_transformer_classifier` — a transformer encoder whose
  FFN blocks are MoE layers, compiled and ready for ``SparkModel``.

Under data-parallel training experts replicate per worker (each worker
routes its own tokens). Under ``SparkModel(model_parallel=N)`` the
planner's expert rules shard the ``[E, ...]`` expert weights over the
``model`` axis — GSPMD places the token all-to-all, giving true expert
parallelism through the same layer.
"""

from __future__ import annotations

_MOE_FFN_CLS = None


def _moe_ffn_layer():
    """The MoeFFN layer class, created lazily (keras under the jax
    backend) and registered with Keras's serializer."""
    global _MOE_FFN_CLS
    if _MOE_FFN_CLS is not None:
        return _MOE_FFN_CLS
    import keras
    import jax.numpy as jnp

    from elephas_tpu.ops.moe import _topk_dispatch

    @keras.saving.register_keras_serializable(package="elephas_tpu")
    class MoeFFN(keras.layers.Layer):
        """Mixture-of-Experts FFN: top-k routed, capacity-bounded, with
        the Switch load-balance loss added during training.

        Replaces a transformer block's dense FFN. Input ``[B, S, D]``
        (or ``[T, D]``); output same shape. Dropped tokens (capacity
        overflow) output zero — wrap the layer with a residual
        connection, as in Switch.
        """

        def __init__(
            self,
            num_experts: int,
            d_hidden: int,
            k: int = 2,
            capacity_factor: float = 1.25,
            aux_weight: float = 1e-2,
            activation: str = "gelu",
            **kwargs,
        ):
            super().__init__(**kwargs)
            if k > num_experts:
                raise ValueError(
                    f"k={k} routing choices exceed num_experts={num_experts}"
                )
            self.num_experts = num_experts
            self.d_hidden = d_hidden
            self.k = k
            self.capacity_factor = capacity_factor
            self.aux_weight = aux_weight
            self.activation = activation

        def build(self, input_shape):
            d = int(input_shape[-1])
            e, h = self.num_experts, self.d_hidden
            init = keras.initializers.VarianceScaling(2.0, "fan_in", "truncated_normal")
            self.gate_kernel = self.add_weight(
                name="gate_kernel", shape=(d, e), initializer="glorot_uniform"
            )
            self.expert_w1 = self.add_weight(
                name="expert_w1", shape=(e, d, h), initializer=init
            )
            self.expert_b1 = self.add_weight(
                name="expert_b1", shape=(e, h), initializer="zeros"
            )
            self.expert_w2 = self.add_weight(
                name="expert_w2", shape=(e, h, d), initializer=init
            )
            self.expert_b2 = self.add_weight(
                name="expert_b2", shape=(e, d), initializer="zeros"
            )
            super().build(input_shape)

        def call(self, x, training=None):
            act = keras.activations.get(self.activation)
            shape = x.shape
            d = shape[-1]
            tokens = x
            if len(shape) == 3:
                tokens = jnp.reshape(x, (-1, d))
            t = tokens.shape[0]
            capacity = max(
                1,
                int(self.k * t * self.capacity_factor / self.num_experts),
            )
            # read .value explicitly: raw keras Variables are not valid
            # JAX types in jnp ops (jax dropped the __jax_array__
            # auto-convert protocol), and under keras' StatelessScope —
            # SparkModel training steps, the serving engine's graph
            # replay — .value resolves to the scope's traced array, so
            # autodiff and GSPMD shardings flow through unchanged.
            # This was the root cause of the seed's 8 MoE/SP tier-1
            # failures (regression-pinned in tests/test_moe.py).
            gate_w = self.gate_kernel.value
            w1, b1 = self.expert_w1.value, self.expert_b1.value
            w2, b2 = self.expert_w2.value, self.expert_b2.value
            dispatch, combine, aux = _topk_dispatch(
                tokens, gate_w, self.num_experts, capacity, k=self.k
            )
            expert_inputs = jnp.einsum("td,tec->ecd", tokens, dispatch)
            h = act(
                jnp.einsum("ecd,edh->ech", expert_inputs, w1)
                + b1[:, None, :]
            )
            out = (
                jnp.einsum("ech,ehd->ecd", h, w2)
                + b2[:, None, :]
            )
            out = jnp.einsum("ecd,tec->td", out, combine)
            if training:
                self.add_loss(self.aux_weight * aux)
            if len(shape) == 3:
                out = jnp.reshape(out, (-1, shape[1], d))
            return out

        def compute_output_shape(self, input_shape):
            # shape-preserving; capacity math needs concrete token counts,
            # so keras must not trace call() symbolically
            return input_shape

        def get_config(self):
            config = super().get_config()
            config.update(
                num_experts=self.num_experts,
                d_hidden=self.d_hidden,
                k=self.k,
                capacity_factor=self.capacity_factor,
                aux_weight=self.aux_weight,
                activation=self.activation,
            )
            return config

    _MOE_FFN_CLS = MoeFFN
    return MoeFFN


def __getattr__(name):
    if name == "MoeFFN":
        return _moe_ffn_layer()
    raise AttributeError(name)


def _switch_block(x, name, num_heads, head_dim, num_experts,
                  expert_hidden, k, capacity_factor, aux_weight,
                  dropout, L, FlashMHA, MoeFFN, causal=False,
                  rope=False):
    """One Switch block (pre-LN attention + routed-expert FFN) —
    shared by the classifier and the causal LM."""
    h = L.LayerNormalization(epsilon=1e-6, name=f"{name}_ln1")(x)
    h = FlashMHA(
        num_heads, head_dim, causal=causal, rope=rope,
        name=f"{name}_attn",
    )(h)
    if dropout > 0:
        h = L.Dropout(dropout, name=f"{name}_drop1")(h)
    x = L.Add(name=f"{name}_res1")([x, h])
    h = L.LayerNormalization(epsilon=1e-6, name=f"{name}_ln2")(x)
    h = MoeFFN(
        num_experts,
        expert_hidden,
        k=k,
        capacity_factor=capacity_factor,
        aux_weight=aux_weight,
        name=f"{name}_moe",
    )(h)
    if dropout > 0:
        h = L.Dropout(dropout, name=f"{name}_drop2")(h)
    return L.Add(name=f"{name}_res2")([x, h])


def switch_transformer_lm(
    vocab_size: int = 20000,
    maxlen: int = 128,
    d_model: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    num_experts: int = 4,
    expert_hidden: int | None = None,
    k: int = 2,
    capacity_factor: float = 1.5,
    aux_weight: float = 1e-2,
    dropout: float = 0.0,
    lr: float = 1e-3,
    seed: int = 0,
    rope: bool = False,
):
    """Causal decoder LM with MoE FFN blocks (Switch-style) — the
    sparse counterpart of
    :func:`~elephas_tpu.models.transformer.transformer_lm` (r5; the
    reference has neither LMs nor MoE — TPU-native extension).

    Composes with the whole surface: trains through ``SparkModel``
    (experts shard over the model axis under ``model_parallel`` — the
    planner's ``expert_w*`` rules), and decodes through
    ``models.generate`` including the KV-cache graph replay (MoE
    routing is token-local, so the per-token replay is exact math).
    Routing CAPACITY note: expert capacity is computed from the tokens
    present in the program — the full-recompute decode routes all
    ``B·maxlen`` positions, the cached decode routes ``B`` per step —
    so capacity-DROPPED tokens can differ between the two paths; with
    enough capacity (``k·capacity_factor ≥ num_experts``) nothing
    drops and the paths agree exactly.
    """
    import keras

    from elephas_tpu.models.transformer import (
        _flash_mha_layer, _positions,
    )

    keras.utils.set_random_seed(seed)
    L = keras.layers
    FlashMHA = _flash_mha_layer()
    MoeFFN = _moe_ffn_layer()
    head_dim = d_model // num_heads
    expert_hidden = expert_hidden or 4 * d_model

    inputs = keras.Input((maxlen,), dtype="int32")
    x = L.Embedding(vocab_size, d_model, name="tok_embed")(inputs)
    if not rope:
        x = x + _positions(maxlen, d_model)[None]
    for b in range(num_layers):
        x = _switch_block(
            x, f"blk{b}", num_heads, head_dim, num_experts,
            expert_hidden, k, capacity_factor, aux_weight, dropout, L,
            FlashMHA, MoeFFN, causal=True, rope=rope,
        )
    x = L.LayerNormalization(epsilon=1e-6, name="final_ln")(x)
    outputs = L.Dense(vocab_size, name="lm_head", dtype="float32")(x)
    model = keras.Model(inputs, outputs, name="switch_transformer_lm")
    model.compile(
        optimizer=keras.optimizers.Adam(lr),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )
    return model


def switch_transformer_classifier(
    vocab_size: int = 20000,
    maxlen: int = 128,
    num_classes: int = 2,
    d_model: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    num_experts: int = 4,
    expert_hidden: int | None = None,
    k: int = 2,
    capacity_factor: float = 1.5,
    aux_weight: float = 1e-2,
    dropout: float = 0.1,
    lr: float = 1e-3,
    seed: int = 0,
):
    """Transformer encoder classifier with MoE FFN blocks (Switch-style).

    Same task shape as
    :func:`~elephas_tpu.models.transformer.transformer_classifier`; the
    dense MLP in each block is replaced by ``num_experts`` routed experts
    with a load-balance auxiliary loss.
    """
    import keras

    from elephas_tpu.models.transformer import _flash_mha_layer, _positions

    keras.utils.set_random_seed(seed)
    L = keras.layers
    FlashMHA = _flash_mha_layer()
    MoeFFN = _moe_ffn_layer()
    head_dim = d_model // num_heads
    expert_hidden = expert_hidden or 4 * d_model

    inputs = keras.Input((maxlen,), dtype="int32")
    x = L.Embedding(vocab_size, d_model, name="tok_embed")(inputs)
    x = x + _positions(maxlen, d_model)[None]
    for b in range(num_layers):
        x = _switch_block(
            x, f"blk{b}", num_heads, head_dim, num_experts,
            expert_hidden, k, capacity_factor, aux_weight, dropout, L,
            FlashMHA, MoeFFN,
        )
    x = L.LayerNormalization(epsilon=1e-6, name="final_ln")(x)
    x = L.GlobalAveragePooling1D(name="pool")(x)
    activation = "sigmoid" if num_classes == 1 else "softmax"
    outputs = L.Dense(num_classes, activation=activation, name="head")(x)
    model = keras.Model(inputs, outputs, name="switch_transformer_classifier")
    loss = (
        "binary_crossentropy"
        if num_classes == 1
        else "sparse_categorical_crossentropy"
    )
    model.compile(
        optimizer=keras.optimizers.Adam(lr), loss=loss, metrics=["accuracy"]
    )
    return model
