"""ResNet — BASELINE config #5 (the north-star throughput model).

Implemented from scratch in Keras-3 functional style (no
``keras.applications`` import, no pretrained-weight downloads — this
environment has zero egress). Standard bottleneck-v1 design: 7×7/2 stem,
max-pool, four stages of [3, 4, 6, 3] bottleneck blocks for ResNet-50.

TPU notes:
- NHWC channels-last, the layout XLA:TPU tiles onto the MXU.
- ``dtype_policy='mixed_bfloat16'`` keeps conv/matmul compute in bf16
  (MXU-native) with float32 variables and softmax.
- BatchNorm statistics are non-trainable float state; the MeshRunner
  ``pmean``s them across workers each sync (SURVEY.md §7 "hard parts").
- A ``depths``/``width`` knob gives a tiny variant for CPU tests and the
  multi-chip dry-run without touching the benchmark architecture.
"""

from __future__ import annotations


def _bottleneck(x, filters: int, stride: int, name: str, L):
    """Bottleneck residual block: 1×1 reduce → 3×3 → 1×1 expand (×4)."""
    shortcut = x
    if stride != 1 or x.shape[-1] != filters * 4:
        shortcut = L.Conv2D(
            filters * 4, 1, strides=stride, use_bias=False, name=name + "_sc_conv"
        )(x)
        shortcut = L.BatchNormalization(name=name + "_sc_bn")(shortcut)

    y = L.Conv2D(filters, 1, use_bias=False, name=name + "_c1")(x)
    y = L.BatchNormalization(name=name + "_bn1")(y)
    y = L.Activation("relu", name=name + "_r1")(y)
    y = L.Conv2D(
        filters, 3, strides=stride, padding="same", use_bias=False, name=name + "_c2"
    )(y)
    y = L.BatchNormalization(name=name + "_bn2")(y)
    y = L.Activation("relu", name=name + "_r2")(y)
    y = L.Conv2D(filters * 4, 1, use_bias=False, name=name + "_c3")(y)
    y = L.BatchNormalization(name=name + "_bn3")(y)
    y = L.Add(name=name + "_add")([shortcut, y])
    return L.Activation("relu", name=name + "_out")(y)


def resnet(
    input_shape: tuple[int, int, int] = (224, 224, 3),
    num_classes: int = 1000,
    depths: tuple[int, ...] = (3, 4, 6, 3),
    width: int = 64,
    lr: float = 0.1,
    momentum: float = 0.9,
    dtype_policy: str | None = None,
    sparse_labels: bool = True,
    seed: int = 0,
    compile_model: bool = True,
):
    """General bottleneck ResNet; ``depths=(3,4,6,3), width=64`` = ResNet-50."""
    import keras

    keras.utils.set_random_seed(seed)
    prev_policy = keras.config.dtype_policy()
    if dtype_policy is not None:
        keras.config.set_dtype_policy(dtype_policy)
    try:
        L = keras.layers
        inputs = keras.Input(input_shape)
        x = L.Conv2D(
            width, 7, strides=2, padding="same", use_bias=False, name="stem_conv"
        )(inputs)
        x = L.BatchNormalization(name="stem_bn")(x)
        x = L.Activation("relu", name="stem_relu")(x)
        x = L.MaxPooling2D(3, strides=2, padding="same", name="stem_pool")(x)
        for stage, blocks in enumerate(depths):
            filters = width * (2**stage)
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = _bottleneck(x, filters, stride, f"s{stage}_b{b}", L)
        x = L.GlobalAveragePooling2D(name="avg_pool")(x)
        x = L.Dense(num_classes, name="head")(x)
        # softmax in float32 even under mixed_bfloat16 (numerics)
        outputs = L.Activation("softmax", dtype="float32", name="probs")(x)
        model = keras.Model(inputs, outputs, name=f"resnet{sum(depths) * 3 + 2}")
    finally:
        if dtype_policy is not None:
            keras.config.set_dtype_policy(prev_policy)

    if compile_model:
        loss = (
            "sparse_categorical_crossentropy"
            if sparse_labels
            else "categorical_crossentropy"
        )
        model.compile(
            optimizer=keras.optimizers.SGD(lr, momentum=momentum),
            loss=loss,
            metrics=["accuracy"],
        )
    return model


def resnet50(
    input_shape: tuple[int, int, int] = (224, 224, 3),
    num_classes: int = 1000,
    **kwargs,
):
    return resnet(input_shape, num_classes, depths=(3, 4, 6, 3), width=64, **kwargs)
