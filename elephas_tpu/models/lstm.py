"""IMDB LSTM text classifier — BASELINE config #4 (sequence/embedding path).

The classic Keras IMDB example the reference lineage demonstrates:
Embedding → LSTM → sigmoid. Static ``maxlen`` keeps shapes fixed so the
whole sequence model lowers through XLA (``lax.scan`` inside the LSTM cell)
without retracing.
"""

from __future__ import annotations


def imdb_lstm(
    vocab_size: int = 20000,
    maxlen: int = 80,
    embed_dim: int = 128,
    units: int = 128,
    lr: float = 1e-3,
    seed: int = 0,
):
    import keras

    keras.utils.set_random_seed(seed)
    L = keras.layers
    model = keras.Sequential(
        [
            L.Input((maxlen,), dtype="int32"),
            L.Embedding(vocab_size, embed_dim),
            L.LSTM(units, dropout=0.2, recurrent_dropout=0.0),
            L.Dense(1, activation="sigmoid"),
        ],
        name="imdb_lstm",
    )
    model.compile(
        optimizer=keras.optimizers.Adam(lr),
        loss="binary_crossentropy",
        metrics=["accuracy"],
    )
    return model
