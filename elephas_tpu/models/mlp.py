"""MNIST-style MLP — BASELINE config #1 (reference example
``[U] elephas examples/mnist_mlp_spark.py``: 784→128→128→10 with dropout,
categorical crossentropy)."""

from __future__ import annotations


def mnist_mlp(
    input_dim: int = 784,
    num_classes: int = 10,
    hidden: int = 128,
    dropout: float = 0.2,
    lr: float = 1e-3,
    sparse_labels: bool = True,
    seed: int = 0,
):
    import keras

    keras.utils.set_random_seed(seed)
    model = keras.Sequential(
        [
            keras.layers.Input((input_dim,)),
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dropout(dropout),
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dropout(dropout),
            keras.layers.Dense(num_classes, activation="softmax"),
        ],
        name="mnist_mlp",
    )
    loss = (
        "sparse_categorical_crossentropy"
        if sparse_labels
        else "categorical_crossentropy"
    )
    model.compile(
        optimizer=keras.optimizers.Adam(lr), loss=loss, metrics=["accuracy"]
    )
    return model
