"""CIFAR-10 convnet — BASELINE config #2 (the async/hogwild benchmark).

Matches the classic Keras CIFAR-10 CNN shape the reference's examples
lineage uses: two conv blocks (32, 64 filters) with max-pooling and
dropout, then a dense head. Channels-last NHWC — the layout XLA:TPU
prefers for convolutions feeding the MXU.
"""

from __future__ import annotations


def cifar10_cnn(
    input_shape: tuple[int, int, int] = (32, 32, 3),
    num_classes: int = 10,
    lr: float = 1e-3,
    sparse_labels: bool = True,
    seed: int = 0,
):
    import keras

    keras.utils.set_random_seed(seed)
    L = keras.layers
    model = keras.Sequential(
        [
            L.Input(input_shape),
            L.Conv2D(32, 3, padding="same", activation="relu"),
            L.Conv2D(32, 3, activation="relu"),
            L.MaxPooling2D(2),
            L.Dropout(0.25),
            L.Conv2D(64, 3, padding="same", activation="relu"),
            L.Conv2D(64, 3, activation="relu"),
            L.MaxPooling2D(2),
            L.Dropout(0.25),
            L.Flatten(),
            L.Dense(512, activation="relu"),
            L.Dropout(0.5),
            L.Dense(num_classes, activation="softmax"),
        ],
        name="cifar10_cnn",
    )
    loss = (
        "sparse_categorical_crossentropy"
        if sparse_labels
        else "categorical_crossentropy"
    )
    model.compile(
        optimizer=keras.optimizers.Adam(lr), loss=loss, metrics=["accuracy"]
    )
    return model
