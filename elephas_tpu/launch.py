"""Process launcher — the ``spark-submit`` analogue for multi-host runs.

Reference equivalent (SURVEY.md §2b): Spark's driver↔executor dispatch.
There, a cluster manager starts executors and ships closures; here, one
Python process per host joins a JAX coordination-service gang
(:mod:`elephas_tpu.parallel.distributed`) and then runs the SAME user
script everywhere — SPMD at the process level, matching how TPU pods are
actually operated.

Two ways to use it:

1. Real cluster: start the same script on every host yourself (or via
   your scheduler) with ``ELEPHAS_COORDINATOR=host0:port``,
   ``ELEPHAS_NUM_PROCESSES=N``, ``ELEPHAS_PROCESS_ID=i`` exported, and
   call ``elephas_tpu.parallel.distributed.initialize()`` first thing.
   On Cloud TPU pods the env is auto-detected and none of this is needed.

2. Single machine (testing / CI): ``python -m elephas_tpu.launch
   --num-processes 2 --cpu-devices-per-process 4 script.py`` spawns the
   gang locally with a virtual CPU mesh per process — the multi-host
   analogue of the reference's Spark ``local[N]`` trick (SURVEY.md §4).

The launcher streams each child's output (prefixed) and exits non-zero
if any child fails — same contract as ``spark-submit``.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_env(
    process_id: int,
    num_processes: int,
    coordinator: str,
    cpu_devices_per_process: int = 0,
    restart_from: str | None = None,
    attempt: int = 0,
) -> dict:
    """Environment for one gang member (exported keys are the public
    launcher contract; see module docstring)."""
    env = dict(os.environ)
    env["ELEPHAS_COORDINATOR"] = coordinator
    env["ELEPHAS_NUM_PROCESSES"] = str(num_processes)
    env["ELEPHAS_PROCESS_ID"] = str(process_id)
    if restart_from:
        env["ELEPHAS_CHECKPOINT_DIR"] = restart_from
    # scripts pass resume=ELEPHAS_RESUME=="1" straight through to fit();
    # restore of an empty checkpoint dir is a fresh start, so exporting
    # "1" from the first attempt would also be safe — "only on restart"
    # just keeps attempt 0's logs free of resume-probe noise
    env["ELEPHAS_RESTART_COUNT"] = str(attempt)
    env["ELEPHAS_RESUME"] = "1" if attempt else "0"
    if cpu_devices_per_process:
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""  # keep TPU plugins out of CPU sim
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{cpu_devices_per_process}"
        ).strip()
    return env


def _run_gang_once(
    script: str,
    script_args: list[str] | None,
    num_processes: int,
    coordinator: str,
    cpu_devices_per_process: int,
    timeout: float | None,
    restart_from: str | None = None,
    attempt: int = 0,
) -> int:
    """One gang generation: spawn, stream prefixed output, fail fast.

    Gang semantics on failure: the FIRST child to exit non-zero kills
    the whole generation immediately (the collective is wedged without
    it — surviving members would block in a collective until the gang
    timeout), so the launcher can relaunch everyone promptly.
    """
    procs = []
    for i in range(num_processes):
        procs.append(
            subprocess.Popen(
                [sys.executable, script, *(script_args or [])],
                env=child_env(
                    i, num_processes, coordinator, cpu_devices_per_process,
                    restart_from=restart_from, attempt=attempt,
                ),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    def stream(i: int, p: subprocess.Popen) -> None:
        for line in p.stdout:
            if not line.endswith("\n"):
                # a child's unterminated final line would otherwise merge
                # with the other process's next line in the combined
                # stream, corrupting machine-read output (RESULT lines)
                line += "\n"
            sys.stdout.write(f"[proc {i}] {line}")
            sys.stdout.flush()

    threads = [
        threading.Thread(target=stream, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    for t in threads:
        t.start()
    import time

    deadline = time.monotonic() + timeout if timeout else None
    rcs = []
    try:
        while True:
            polled = [p.poll() for p in procs]
            if all(rc is not None for rc in polled):
                rcs = polled
                break
            failed = [
                i for i, rc in enumerate(polled) if rc not in (None, 0)
            ]
            if failed:
                sys.stdout.write(
                    f"[launch] proc {failed[0]} exited rc="
                    f"{polled[failed[0]]}; killing the gang\n"
                )
                # the FIRST failing child's real code is the gang's exit
                # code — siblings are about to be killed (-9) and their
                # placeholder must not mask it (code-review r4)
                rcs = [polled[failed[0]]]
                break
            if deadline and time.monotonic() > deadline:
                sys.stdout.write("[launch] gang timed out; killing children\n")
                rcs = [124]  # timeout exit code, not an escaping exception
                break
            time.sleep(0.1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for t in threads:
        t.join(timeout=5)
    return max(abs(rc) for rc in rcs) if rcs else 1


def launch(
    script: str,
    script_args: list[str] | None = None,
    num_processes: int = 2,
    coordinator: str | None = None,
    cpu_devices_per_process: int = 0,
    timeout: float | None = None,
    max_restarts: int = 0,
    restart_from: str | None = None,
) -> int:
    """Spawn the gang; stream prefixed output; return max child exit code.

    With ``max_restarts > 0`` the launcher is the failure-recovery loop
    the reference delegates to Spark (``spark.task.maxFailures``,
    SURVEY.md §5): any child death kills the whole gang generation and
    a fresh gang is relaunched — up to ``max_restarts`` times — with
    ``ELEPHAS_RESUME=1`` exported so the script's
    ``fit(checkpoint_dir=os.environ["ELEPHAS_CHECKPOINT_DIR"],
    resume=...)`` continues from the newest snapshot under
    ``restart_from``. A fresh coordinator port is chosen per generation
    (unless pinned explicitly), so a half-dead coordination service
    can't wedge the relaunch.
    """
    for attempt in range(max_restarts + 1):
        rc = _run_gang_once(
            script, script_args, num_processes,
            coordinator or f"127.0.0.1:{free_port()}",
            cpu_devices_per_process, timeout,
            restart_from=restart_from, attempt=attempt,
        )
        if rc == 0 or attempt == max_restarts:
            return rc
        sys.stdout.write(
            f"[launch] gang generation {attempt} failed (rc={rc}); "
            f"restarting ({attempt + 1}/{max_restarts})"
            + (f" from {restart_from}\n" if restart_from else "\n")
        )
        sys.stdout.flush()
    return rc


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m elephas_tpu.launch", description=__doc__
    )
    p.add_argument("--num-processes", type=int, default=2)
    p.add_argument("--coordinator", default=None, help="host:port of process 0")
    p.add_argument(
        "--cpu-devices-per-process",
        type=int,
        default=0,
        help="simulate with N virtual CPU devices per process (testing)",
    )
    p.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="relaunch the whole gang up to N times after a child failure "
             "(elastic checkpoint-restart; pair with --restart-from)",
    )
    p.add_argument(
        "--restart-from",
        default=None,
        metavar="CKPT_DIR",
        help="checkpoint dir exported to children as "
             "ELEPHAS_CHECKPOINT_DIR; restarted generations also get "
             "ELEPHAS_RESUME=1 so fit() resumes from the newest snapshot",
    )
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    return launch(
        args.script,
        args.script_args,
        num_processes=args.num_processes,
        coordinator=args.coordinator,
        cpu_devices_per_process=args.cpu_devices_per_process,
        max_restarts=args.max_restarts,
        restart_from=args.restart_from,
    )


if __name__ == "__main__":
    sys.exit(main())
